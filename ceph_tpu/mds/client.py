"""The capability-aware FS client (src/client/Client.cc reduced).

Metadata goes through the MDS session (MClientRequest); file DATA is
striped straight to the data pool with the REAL CephFS object naming
(<ino:x>.<objno:08x>), exactly like the kernel/fuse clients talk to
OSDs directly.  readdir/stat results are cached while the MDS-granted
capability stands; a cap recall (MClientCaps revoke, pushed by the
MDS before a sibling's conflicting mutation commits) invalidates the
cache — coherence by recall, not by polling.

Failover: when the MDS connection dies the client re-resolves the
active MDS from the monitor ("mds stat"), reopens its session (the
reference's reconnect phase; all caps are implicitly dropped) and
retries the op.  Retried mutations reconcile at-least-once delivery:
a retry observing EEXIST (mkdir/create) or ENOENT (unlink/rmdir/
rename-src) after a reconnect checks whether the FIRST attempt
already landed and treats that as success — the reference dedups via
session reqids, which die with the failed MDS here too (sessions are
in-memory; deviation documented in the package docstring).
"""

from __future__ import annotations

import itertools
import json
import stat as statmod
import threading
import time

from ..msg import Messenger
from ..msg.message import MClientCaps, MClientReply, MClientRequest
from ..msg.messenger import Connection, Dispatcher, MessageError
from ..fs import _data_oid  # one definition of the on-disk naming
from ..osdc.objecter import ObjectNotFound, RadosError
from ..osdc.striper import StripeLayout, map_extent


class MDSError(RadosError):
    def __init__(self, rc: int, msg: str):
        super().__init__(msg)
        self.rc = rc


class MDSClient(Dispatcher):
    """One mounted filesystem through the MDS tier."""

    def __init__(
        self,
        rados,
        data_pool: str,
        name: str = "client",
        layout: StripeLayout | None = None,
        op_timeout: float = 30.0,
    ):
        self.rados = rados
        self.data = rados.open_ioctx(data_pool)
        self.layout = layout or StripeLayout(
            stripe_unit=1 << 20, stripe_count=1, object_size=1 << 22
        )
        self.name = name
        self.op_timeout = op_timeout
        self.msgr = Messenger(f"fsclient.{name}")
        self.msgr.add_dispatcher(self)
        self.msgr.start()
        self._lock = threading.RLock()
        # one session per ACTIVE RANK (multi-MDS): ops route to the
        # subtree's auth rank by longest-prefix match of the client's
        # copy of the mon's stable pin table
        self._conns: dict[int, Connection] = {}
        self._subtrees: dict[str, int] = {"/": 0}
        # caches valid while the cap stands: ino -> payload, plus the
        # path -> ino tags to invalidate on recall
        self._dir_cache: dict[int, dict] = {}
        self._stat_cache: dict[str, dict] = {}
        self._reqids = itertools.count(1)
        self.recalls = 0  # observability: cap revokes received
        # bumped on every recall: an in-flight readdir/stat must not
        # cache its reply if a recall landed while it was pending
        # (the reply could predate the mutation the recall fences)
        self._recall_gen = 0
        self._connect()

    def close(self) -> None:
        self.msgr.shutdown()

    # -- session / failover / routing --------------------------------------
    def _mdsmap(self) -> dict:
        rc, outb, outs = self.rados.mon_command({"prefix": "mds stat"})
        if rc != 0:
            raise MDSError(rc, outs)
        m = json.loads(outb)
        if not m.get("actives"):
            raise MDSError(-11, "no active mds (-EAGAIN)")
        with self._lock:
            self._subtrees = dict(m.get("subtrees") or {"/": 0})
        return m

    def _auth_rank(self, path: str) -> int:
        """Longest-prefix match against the stable pin table (the
        client-side half of subtree delegation: ops go straight to
        the auth rank)."""
        from . import subtree_auth_rank

        with self._lock:
            table = dict(self._subtrees)
        return subtree_auth_rank(table, path)

    def _connect(self, rank: int = 0) -> Connection:
        m = self._mdsmap()
        addr = m["actives"].get(str(rank))
        if addr is None:
            raise MDSError(-11, f"no active mds rank {rank} (-EAGAIN)")
        addr = addr["addr"] if isinstance(addr, dict) else addr
        old = self._conns.get(rank)
        if old is not None and not old.is_closed:
            try:
                old.close()
            except (MessageError, OSError):
                pass
        host, _, port = addr.rpartition(":")
        conn = self.msgr.connect(host, int(port))
        reply = conn.call(
            MClientRequest(
                op="open_session",
                args=json.dumps({"name": self.name}),
            ),
            timeout=10.0,
        )
        if not isinstance(reply, MClientReply) or reply.rc != 0:
            raise MDSError(-5, "session open failed")
        with self._lock:
            self._conns[rank] = conn
            # a fresh session holds no caps: nothing cached is covered
            self._dir_cache.clear()
            self._stat_cache.clear()
        return conn

    def _call(
        self,
        op: str,
        args: dict,
        reqid: str | None = None,
        path: str | None = None,
    ):
        """One metadata op with failover retry and subtree routing:
        a -ESTALE "not auth" reply refreshes the pin table and
        re-routes (the reference MDS forwards instead)."""
        deadline = time.monotonic() + self.op_timeout
        reqid = reqid or f"{self.name}.{next(self._reqids)}"
        retried = False
        rank = self._auth_rank(path) if path is not None else 0
        while True:
            conn = self._conns.get(rank)
            try:
                if conn is None or conn.is_closed:
                    raise MessageError("no mds connection")
                reply = conn.call(
                    MClientRequest(
                        op=op, args=json.dumps(args), reqid=reqid
                    ),
                    timeout=10.0,
                )
                if not isinstance(reply, MClientReply):
                    raise MessageError("bad reply")
                if reply.rc == -11:  # mds not active: map is moving
                    raise MessageError(reply.outs)
                if reply.rc == -116:
                    # not the auth (our table is stale): refresh and
                    # re-route to the hinted/looked-up rank
                    if time.monotonic() >= deadline:
                        raise MDSError(-110, "mds re-route timeout")
                    try:
                        self._mdsmap()
                    except MDSError:
                        # actives momentarily empty mid-failover:
                        # keep the retry budget, not a hard error
                        time.sleep(0.25)
                        continue
                    new_rank = (
                        self._auth_rank(path)
                        if path is not None
                        else 0
                    )
                    if new_rank == rank:
                        time.sleep(0.25)  # table still propagating
                    rank = new_rank
                    if rank not in self._conns or (
                        self._conns[rank] is None
                        or self._conns[rank].is_closed
                    ):
                        try:
                            self._connect(rank)
                        except (MDSError, MessageError, OSError):
                            time.sleep(0.25)
                    continue
                if reply.rc != 0:
                    if retried:
                        out = self._retry_outcome(op, args, reply)
                        if out is not None:
                            return out
                    raise MDSError(reply.rc, reply.outs)
                return json.loads(reply.outb)
            except (MessageError, OSError) as e:
                if time.monotonic() >= deadline:
                    raise MDSError(-110, f"mds op timeout: {e}")
                retried = True
                time.sleep(0.25)
                if path is not None:
                    rank = self._auth_rank(path)
                try:
                    self._connect(rank)
                except (MDSError, MessageError, OSError):
                    continue

    @staticmethod
    def _dirof(path: str) -> str:
        from . import path_dirname

        return path_dirname(path)

    def _retry_outcome(self, op, args, reply) -> dict | None:
        """At-least-once reconciliation after a failover retry: the
        first attempt may have committed before the MDS died."""
        if reply.rc == -17 and op in ("mkdir", "create"):
            st = self._call(
                "stat", {"path": args["path"]}, path=args["path"]
            )
            want = "dir" if op == "mkdir" else "file"
            if st.get("type") == want:
                return {"ino": st["ino"]}
        if reply.rc == -2 and op in ("unlink", "rmdir"):
            return {}
        if reply.rc == -2 and op == "rename":
            try:
                self._call(
                    "stat", {"path": args["dst"]}, path=args["dst"]
                )
                return {}
            except MDSError:
                pass
        return None

    # -- cap recall --------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if not isinstance(msg, MClientCaps) or msg.action != "revoke":
            return False
        with self._lock:
            self.recalls += 1
            self._recall_gen += 1
            self._dir_cache.pop(msg.ino, None)
            self._stat_cache = {
                p: st
                for p, st in self._stat_cache.items()
                if st["ino"] != msg.ino and st["_pino"] != msg.ino
            }
        try:
            conn.send(MClientCaps(action="ack", ino=msg.ino, tid=msg.tid))
        except (MessageError, OSError):
            pass
        return True

    def ms_handle_reset(self, conn: Connection) -> None:
        with self._lock:
            for rank, c in list(self._conns.items()):
                if c is conn:
                    self._conns.pop(rank, None)
                    self._dir_cache.clear()
                    self._stat_cache.clear()

    # -- metadata verbs ----------------------------------------------------
    def _local_invalidate(self, *paths: str) -> None:
        """Drop OWN cached state touched by an own mutation — the MDS
        exempts the requester from the cap recall (it just told us),
        so self-coherence is the client's job."""
        with self._lock:
            for p in paths:
                st = self._stat_cache.pop(p, None)
                if st is not None:
                    self._dir_cache.pop(st["ino"], None)
                parts = [x for x in p.split("/") if x]
                parent = "/".join(parts[:-1])
                pst = self._stat_cache.get(parent)
                if pst is not None:
                    self._dir_cache.pop(pst["ino"], None)
                else:
                    # parent ino unknown (its stat was never cached):
                    # a targeted drop is impossible, and a stale
                    # parent listing would show the old name — clear
                    # the dir cache conservatively
                    self._dir_cache.clear()

    def mkdir(self, path: str) -> int:
        out = self._call("mkdir", {"path": path},
                         path=self._dirof(path))
        self._local_invalidate(path)
        return out["ino"]

    def rmdir(self, path: str) -> None:
        self._call("rmdir", {"path": path}, path=self._dirof(path))
        self._local_invalidate(path)

    def create(self, path: str) -> int:
        out = self._call("create", {"path": path},
                         path=self._dirof(path))
        self._local_invalidate(path)
        return out["ino"]

    def rename(self, src: str, dst: str) -> None:
        self._call("rename", {"src": src, "dst": dst},
                   path=self._dirof(src))
        self._local_invalidate(src, dst)

    def readdir(self, path: str = "/") -> list[str]:
        with self._lock:
            st = self._stat_cache.get(path)
            if st is not None and st["ino"] in self._dir_cache:
                return sorted(self._dir_cache[st["ino"]])
        with self._lock:
            gen = self._recall_gen
        out = self._call("readdir", {"path": path}, path=path)
        with self._lock:
            if self._recall_gen == gen:
                self._dir_cache[out["ino"]] = out["entries"]
        return sorted(out["entries"])

    def stat(self, path: str) -> dict:
        with self._lock:
            st = self._stat_cache.get(path)
            if st is not None:
                return dict(st)
        with self._lock:
            gen = self._recall_gen
        out = self._call("stat", {"path": path}, path=path)
        st = {
            "ino": out["ino"],
            "type": out["type"],
            "size": out["size"],
            "mtime": out["mtime"],
            "mode": (
                statmod.S_IFDIR
                if out["type"] == "dir"
                else statmod.S_IFREG
            ),
            "_pino": self._parent_ino_tag(path),
        }
        with self._lock:
            if self._recall_gen == gen:
                self._stat_cache[path] = st
        return dict(st)

    def _parent_ino_tag(self, path: str) -> int:
        """Tag cached stats with the parent dir's ino when we hold it
        cached, so a recall on the DIRECTORY also drops child stats
        (the dentry lease rides the dir cap here)."""
        parts = [p for p in path.split("/") if p]
        parent = "/".join(parts[:-1])
        with self._lock:
            st = self._stat_cache.get(parent)
            return st["ino"] if st is not None else -1

    def unlink(self, path: str) -> None:
        out = self._call("unlink", {"path": path},
                         path=self._dirof(path))
        self._local_invalidate(path)
        ino = out.get("ino")
        if ino is not None:
            prefix = f"{ino:x}."
            for oid in self.data.list_objects():
                if oid.startswith(prefix):
                    try:
                        self.data.remove(oid)
                    except (ObjectNotFound, RadosError):
                        pass

    # -- file I/O (client -> data pool directly) ---------------------------
    def write(self, path: str, offset: int, data: bytes) -> int:
        st = self.stat(path)
        if st["type"] != "file":
            raise MDSError(-21, f"{path!r}: not a file (-EISDIR)")
        data = bytes(data)
        pos = 0
        for objectno, obj_off, n in map_extent(
            self.layout, offset, len(data)
        ):
            self.data.write(
                _data_oid(st["ino"], objectno),
                data[pos : pos + n],
                offset=obj_off,
            )
            pos += n
        # size/mtime flush to the MDS (the cap-flush analog)
        self._call(
            "setattr",
            {
                "path": path,
                "attrs": {
                    "size": offset + len(data),
                    "mtime": time.time(),
                },
                "grow_only": True,
            },
            path=path,
        )
        with self._lock:
            self._stat_cache.pop(path, None)
        return len(data)

    def read(self, path: str, offset: int = 0, length: int = -1) -> bytes:
        st = self.stat(path)
        if st["type"] != "file":
            raise MDSError(-21, f"{path!r}: not a file (-EISDIR)")
        size = st["size"]
        if length < 0:
            length = size - offset
        length = max(0, min(length, size - offset))
        if length == 0:
            return b""
        parts = []
        for objectno, obj_off, n in map_extent(
            self.layout, offset, length
        ):
            try:
                got = self.data.read(
                    _data_oid(st["ino"], objectno),
                    length=n,
                    offset=obj_off,
                )
            except (ObjectNotFound, RadosError):
                got = b""
            parts.append(got + b"\0" * (n - len(got)))
        return b"".join(parts)

    def truncate(self, path: str, size: int) -> None:
        st = self.stat(path)
        if st["type"] != "file":
            raise MDSError(-21, f"{path!r}: not a file (-EISDIR)")
        if size < st["size"]:
            for objectno, obj_off, n in map_extent(
                self.layout, size, st["size"] - size
            ):
                try:
                    self.data.write(
                        _data_oid(st["ino"], objectno),
                        b"\0" * n,
                        offset=obj_off,
                    )
                except RadosError:
                    pass
        self._call(
            "setattr", {"path": path, "attrs": {"size": size}},
            path=path,
        )
        with self._lock:
            self._stat_cache.pop(path, None)
