"""Replayable metadata journal on rados (src/osdc/Journaler.cc:1).

The reference journals every metadata mutation into a striped object
stream ahead of lazily flushing the cache to the backing dirfrag
objects; on MDS failover the standby replays the stream from the
expire position to rebuild the unflushed tail.  Same shape here:

- head object ``<prefix>.head``: JSON {write_pos, expire_pos} — the
  Journaler::Header (write_pos/expire_pos/trimmed_pos collapsed to
  the two positions this machinery needs).
- entry stream striped over ``<prefix>.<objno:08x>`` objects of fixed
  ``object_size``; each entry is a 4-byte LE length frame + payload
  and may span object boundaries (the reference's journal stripes the
  same way through the Filer).

Durability contract: ``append`` buffers; ``flush`` writes the data
extents FIRST and the head LAST, so a torn flush is re-read as "tail
not yet committed" — replay stops at the recorded write_pos, never
mid-frame.
"""

from __future__ import annotations

import json
import struct

from ..osdc.objecter import ObjectNotFound, RadosError

_LEN = struct.Struct("<I")


class Journaler:
    """One journal stream bound to an ioctx (metadata pool)."""

    def __init__(
        self, ioctx, prefix: str = "mds_journal", object_size: int = 1 << 16
    ):
        self.ioctx = ioctx
        self.prefix = prefix
        self.object_size = object_size
        self.write_pos = 0
        self.expire_pos = 0
        self._pending: list[bytes] = []

    def _oid(self, objno: int) -> str:
        return f"{self.prefix}.{objno:08x}"

    def _head_oid(self) -> str:
        return f"{self.prefix}.head"

    # -- head --------------------------------------------------------------
    def load(self) -> "Journaler":
        """Read the head (or start fresh when none exists)."""
        try:
            head = json.loads(self.ioctx.read(self._head_oid()))
            self.write_pos = int(head["write_pos"])
            self.expire_pos = int(head["expire_pos"])
        except (ObjectNotFound, RadosError, ValueError, KeyError):
            self.write_pos = 0
            self.expire_pos = 0
        return self

    def _write_head(self) -> None:
        self.ioctx.write_full(
            self._head_oid(),
            json.dumps(
                {
                    "write_pos": self.write_pos,
                    "expire_pos": self.expire_pos,
                }
            ).encode(),
        )

    # -- append / flush ----------------------------------------------------
    def append(self, entry: bytes) -> int:
        """Buffer one entry; returns the stream position its frame
        ends at once flushed."""
        frame = _LEN.pack(len(entry)) + bytes(entry)
        self._pending.append(frame)
        return self.write_pos + sum(len(f) for f in self._pending)

    def flush(self) -> int:
        """Write buffered frames (data first, head last); returns the
        new write_pos."""
        if not self._pending:
            return self.write_pos
        blob = b"".join(self._pending)
        self._pending.clear()
        pos = self.write_pos
        off = 0
        while off < len(blob):
            objno, obj_off = divmod(pos + off, self.object_size)
            n = min(self.object_size - obj_off, len(blob) - off)
            self.ioctx.write(
                self._oid(objno), blob[off : off + n], offset=obj_off
            )
            off += n
        self.write_pos = pos + len(blob)
        self._write_head()
        return self.write_pos

    # -- replay ------------------------------------------------------------
    def _read_stream(self, pos: int, length: int) -> bytes:
        parts = []
        while length > 0:
            objno, obj_off = divmod(pos, self.object_size)
            n = min(self.object_size - obj_off, length)
            try:
                got = self.ioctx.read(
                    self._oid(objno), length=n, offset=obj_off
                )
            except (ObjectNotFound, RadosError):
                got = b""
            parts.append(got + b"\0" * (n - len(got)))
            pos += n
            length -= n
        return b"".join(parts)

    def replay(self):
        """Yield every committed entry in [expire_pos, write_pos) —
        the standby's journal replay on takeover."""
        for entry, _end in self.replay_from(self.expire_pos):
            yield entry

    # -- registered clients (Journaler client registry role,
    # src/journal/JournalMetadata.cc: a tailing consumer — rbd-mirror —
    # records its replay position; trim never passes the slowest
    # client).  Positions live in a SEPARATE omap object so consumer
    # updates never race the owner's head writes. ---------------------------
    def _clients_oid(self) -> str:
        return f"{self.prefix}.clients"

    def register_client(self, cid: str) -> int:
        """Idempotent; a new client starts at the current expire_pos
        (everything earlier is already in the backing store)."""
        existing = self.client_pos(cid)
        if existing is not None:
            return existing
        try:
            self.ioctx.stat(self._clients_oid())
        except (ObjectNotFound, RadosError):
            self.ioctx.write_full(self._clients_oid(), b"")
        self.ioctx.omap_set(
            self._clients_oid(),
            {f"client.{cid}": str(self.expire_pos).encode()},
        )
        return self.expire_pos

    def update_client(self, cid: str, pos: int) -> None:
        self.ioctx.omap_set(
            self._clients_oid(), {f"client.{cid}": str(pos).encode()}
        )

    def unregister_client(self, cid: str) -> None:
        try:
            self.ioctx.omap_rm_keys(
                self._clients_oid(), [f"client.{cid}"]
            )
        except (ObjectNotFound, RadosError):
            pass

    def client_pos(self, cid: str) -> int | None:
        try:
            vals = self.ioctx.omap_get_vals(self._clients_oid())
        except (ObjectNotFound, RadosError):
            return None
        raw = vals.get(f"client.{cid}")
        return int(raw) if raw is not None else None

    def _clients_min(self) -> int | None:
        try:
            vals = self.ioctx.omap_get_vals(self._clients_oid())
        except (ObjectNotFound, RadosError):
            return None
        poss = [
            int(v) for k, v in vals.items()
            if k.startswith("client.")
        ]
        return min(poss) if poss else None

    def replay_from(self, pos: int):
        """Yield (entry, end_pos) from ``pos`` to the committed head
        — the tailing-consumer read (rbd-mirror's journal fetch)."""
        pos = max(pos, self.expire_pos)
        while pos + _LEN.size <= self.write_pos:
            (n,) = _LEN.unpack(self._read_stream(pos, _LEN.size))
            if pos + _LEN.size + n > self.write_pos:
                break
            yield self._read_stream(pos + _LEN.size, n), (
                pos + _LEN.size + n
            )
            pos += _LEN.size + n

    # -- trim --------------------------------------------------------------
    def trim(self, upto: int | None = None) -> None:
        """Advance expire_pos (everything before it is reflected in
        the backing store) and delete fully-expired stream objects.
        Never trims past the slowest REGISTERED client (rbd-mirror
        must see every entry before it is deleted)."""
        upto = self.write_pos if upto is None else upto
        cmin = self._clients_min()
        if cmin is not None:
            upto = min(upto, cmin)
        old_obj = self.expire_pos // self.object_size
        # NEVER regress: a client registered from a stale instance
        # may record a position below the already-trimmed prefix
        self.expire_pos = max(
            self.expire_pos, min(upto, self.write_pos)
        )
        self._write_head()
        for objno in range(old_obj, self.expire_pos // self.object_size):
            try:
                self.ioctx.remove(self._oid(objno))
            except (ObjectNotFound, RadosError):
                pass
