"""MDS — the metadata daemon tier over rados
(src/mds/Server.cc + src/mds/Locker.cc + src/osdc/Journaler.cc,
reduced to the load-bearing machinery; see docs/PARITY.md).

Three pieces:

- ``Journaler`` (journaler.py): the replayable metadata journal on
  rados — a striped entry stream with a head object tracking
  write/expire positions (src/osdc/Journaler.cc:1).
- ``MDSDaemon`` (server.py): client sessions, a path-walked metadata
  cache journaled ahead of lazy backing-store flushes, capability
  grant/recall for coherent client caching, and mon-driven
  active/standby failover (beacons through the monitor's command
  plane; the MDSMonitor role).
- ``MDSClient`` (client.py): the capability-aware mount — metadata
  through the MDS session, file DATA striped straight to the data
  pool with the real CephFS object naming, readdir/stat caching valid
  exactly while the MDS-granted capability stands.

The cap-free library-mode client (dirfrags-in-omap, single writer)
remains at ceph_tpu.fs.CephFS.
"""


def subtree_auth_rank(table: dict, path: str) -> int:
    """Longest-prefix match of ``path`` against a subtree pin table
    (the MDCache subtree-auth resolution rule).  SHARED between the
    MDS server's enforcement and the client's routing: the two ends
    must agree on this protocol invariant or clients spin on
    -ESTALE."""
    parts = [p for p in path.split("/") if p]
    best, bestlen = 0, -1
    for pref, r in table.items():
        pp = [x for x in pref.split("/") if x]
        if parts[: len(pp)] == pp and len(pp) > bestlen:
            best, bestlen = r, len(pp)
    return best


def path_dirname(path: str) -> str:
    """Parent directory of a slash path ('/' for top-level names)."""
    parts = [p for p in path.split("/") if p]
    return "/" + "/".join(parts[:-1])


from .journaler import Journaler  # noqa: E402
from .server import MDSDaemon  # noqa: E402
from .client import MDSClient, MDSError  # noqa: E402

__all__ = [
    "Journaler", "MDSDaemon", "MDSClient", "MDSError",
    "subtree_auth_rank", "path_dirname",
]
