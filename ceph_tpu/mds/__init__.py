"""MDS — the metadata daemon tier over rados
(src/mds/Server.cc + src/mds/Locker.cc + src/osdc/Journaler.cc,
reduced to the load-bearing machinery; see docs/PARITY.md).

Three pieces:

- ``Journaler`` (journaler.py): the replayable metadata journal on
  rados — a striped entry stream with a head object tracking
  write/expire positions (src/osdc/Journaler.cc:1).
- ``MDSDaemon`` (server.py): client sessions, a path-walked metadata
  cache journaled ahead of lazy backing-store flushes, capability
  grant/recall for coherent client caching, and mon-driven
  active/standby failover (beacons through the monitor's command
  plane; the MDSMonitor role).
- ``MDSClient`` (client.py): the capability-aware mount — metadata
  through the MDS session, file DATA striped straight to the data
  pool with the real CephFS object naming, readdir/stat caching valid
  exactly while the MDS-granted capability stands.

The cap-free library-mode client (dirfrags-in-omap, single writer)
remains at ceph_tpu.fs.CephFS.
"""

from .journaler import Journaler
from .server import MDSDaemon
from .client import MDSClient, MDSError

__all__ = ["Journaler", "MDSDaemon", "MDSClient", "MDSError"]
