"""The MDS daemon: sessions, caps, journaled metadata cache, failover
(src/mds/Server.cc + src/mds/Locker.cc + src/mds/MDCache.h reduced to
the load-bearing machinery).

Shape of the reference this mirrors:

- **Sessions** (Server.cc handle_client_session): clients open a
  session over the messenger; every metadata op arrives as an
  MClientRequest on it.
- **Journal-ahead metadata** (MDLog/EUpdate): every mutation is
  journaled to rados (Journaler) and applied to the in-memory cache
  BEFORE the reply; the backing dirfrag/inode omap objects (the same
  layout ceph_tpu.fs uses) are flushed lazily every
  ``flush_every`` mutations, then the journal is trimmed — so a
  standby taking over replays the journal tail to rebuild exactly the
  unflushed mutations.
- **Capabilities** (Locker.cc): readdir/stat grant the session a
  read-caching cap on the inode; a conflicting mutation REVOKES every
  other session's cap (MClientCaps round trip) before it commits, so
  a client whose sibling just created a file learns by recall, not by
  polling.
- **Mon-driven failover** (MDSMonitor role): daemons beacon the
  monitor ("mds beacon" on the command plane); the monitor holds the
  mdsmap (one active + standbys), promotes a standby when the
  active's beacons stop, and the promoted daemon replays the journal
  before serving.

- **Multi-MDS subtree delegation** (MDCache subtree auth,
  src/mds/MDCache.cc:1, + Migrator export, src/mds/Migrator.cc:1,
  reduced): up to max_mds actives, each auth for the pinned subtrees
  the mon's table assigns it (longest-prefix match), each journaling
  its own rank's mutations.  Because metadata lives in shared rados
  omap objects and caches load lazily, EXPORT is a flush + table
  flip + cap revoke (with a mon-side barrier: clients only see a
  table every active has flushed under) instead of a cache-streaming
  state machine.  Cross-subtree renames run as an MDS→MDS
  ``peer_link`` sub-op (the slave-request seat) followed by the
  local unlink.  A demoted/replaced active is blocklist-fenced via
  its beacon-carried client id.

Deviations (documented): caps are per-inode read-caching only (no
cap bits spectrum, no file-data leases — file DATA goes
client→rados directly), sessions/caps are in-memory (clients
re-open sessions after failover, as in the reference's reconnect
phase), cap coherence is per-rank (a mutation revokes only the auth
rank's sessions; cross-rank readers of boundary dirfrags read fresh
instead), and a crashed cross-rename can leave the name briefly
visible in both directories (link-then-unlink order — never lost).
"""

from __future__ import annotations

import json
import queue
import threading
import time

from ..common import crash as crash_util
from ..common import lockdep
from ..common.log_client import LogClient
from ..msg import Messenger
from ..msg.message import (
    MClientCaps,
    MClientReply,
    MClientRequest,
    MessageError,
)
from ..msg.messenger import Connection, Dispatcher
from .journaler import Journaler

from ..fs import ROOT_INO, _dir_oid, _ino_oid  # shared on-disk naming


class _Session:
    def __init__(self, conn: Connection, name: str):
        self.conn = conn
        self.name = name
        self.caps: set[int] = set()
        # recent reqid -> reply payload (op dedup across client
        # retries on a live session; lost on failover — the client
        # reconciles, see MDSClient._retry_outcome)
        self.replies: dict[str, tuple[int, str, str]] = {}


class MDSDaemon(Dispatcher):
    """One metadata daemon (active or standby)."""

    def __init__(
        self,
        name: str,
        rados,
        meta_pool: str,
        beacon_interval: float = 0.5,
        flush_every: int = 16,
        shared_services: bool | None = None,
    ):
        self.name = name
        self.rados = rados
        self.meta = rados.open_ioctx(meta_pool)
        self.journal = Journaler(self.meta)
        self.flush_every = flush_every
        self.beacon_interval = beacon_interval
        self.state = "standby"
        self.mdsmap_epoch = 0
        # multi-MDS (subtree delegation, MDCache subtree auth +
        # Migrator reduced): my rank, the subtree auth table, and the
        # peer actives' addresses — all distributed via beacons
        self.rank = -1
        self.ops_served = 0  # observability: which actives take traffic
        self._subtrees: dict[str, int] = {"/": 0}
        self._applied_table_epoch = 0
        self._peer_addrs: dict[int, str] = {}
        self._peer_conns: dict[int, Connection] = {}
        # shrink adoption (mon stray_ranks protocol): (rank, gen)
        # pairs whose journals WE replayed; acked on the next beacon
        # so the mon drains its queue and lets the re-pinned table
        # stabilize.  The generation tag pins each ack to ONE
        # eviction: a stale ack can never drain a newer eviction of
        # the same rank whose journal we have not replayed yet
        self._adopted_ranks: set[tuple[int, int]] = set()
        self.adopted_entries = 0  # observability/test hook

        # metadata cache (MDCache role): dirfrags + inodes, loaded
        # lazily from the backing omap, mutated ahead of lazy flushes
        self._lock = lockdep.RMutex("mds.cache")
        self._dirs: dict[int, dict[str, dict]] = {}
        self._inodes: dict[int, dict] = {}
        self._dirty_dentries: dict[int, dict[str, dict | None]] = {}
        self._dirty_inodes: set[int] = set()
        self._removed_inodes: set[int] = set()
        self._next_ino = 0
        self._unflushed = 0

        self._sessions: dict[Connection, _Session] = {}
        self._cap_holders: dict[int, set[_Session]] = {}

        # cluster log + crash capture: entries drain to the mon on the
        # beacon cadence; crash reports join the process-global queue
        # the mgr crash module drains (no mgr session on the MDS)
        self._log_client = LogClient(f"mds.{name}")
        self.clog = self._log_client.channel()

        self.msgr = Messenger(f"mds.{name}")
        self.msgr.add_dispatcher(self)
        self.addr = "%s:%d" % self.msgr.bind()
        self._stop = threading.Event()
        # ops run on a worker thread, NEVER on the messenger loop: a
        # cap revoke is a blocking conn.call, and blocking calls from
        # the loop thread deadlock (the op_shardedwq rule every
        # daemon here follows)
        self._workq: queue.Queue = queue.Queue()
        self.shared_services = bool(shared_services)
        self._worker = None
        self._beacon_thread = None
        self._beacon_handle = None
        if self.shared_services:
            # zero dedicated threads: ops drain through a serial
            # strand on the shared stack (same FIFO semantics as the
            # worker thread), beacons ride a stack timer
            stack = self.msgr._stack
            self._work_strand = stack.offload.strand()
            self._beacon_handle = stack.timers.every(
                self.beacon_interval, self._beacon_once,
                fire_now=True,
            )
        else:
            self._worker = threading.Thread(
                target=self._work_loop, name=f"mds.{name}.worker",
                daemon=True,
            )
            self._worker.start()
            self._beacon_thread = threading.Thread(
                target=self._beacon_loop, name=f"mds.{name}.beacon",
                daemon=True,
            )
            self._beacon_thread.start()

    # -- lifecycle ---------------------------------------------------------
    def shutdown(self) -> None:
        self._stop.set()
        self._workq.put(None)
        if self._beacon_handle is not None:
            self._beacon_handle.cancel()
        if self._beacon_thread is not None:
            self._beacon_thread.join(timeout=5)
        if self._worker is not None:
            self._worker.join(timeout=5)
        if self.state == "active":
            with self._lock:
                try:
                    self._flush()
                except Exception:  # noqa: BLE001 — shutdown best-effort
                    pass
        self.msgr.shutdown()

    def _beacon_loop(self) -> None:
        while not self._stop.is_set():
            self._beacon_once()
            self._stop.wait(self.beacon_interval)

    def _beacon_once(self) -> None:
        if not self._stop.is_set():
            try:
                beacon = {
                    "prefix": "mds beacon",
                    "name": self.name,
                    "addr": self.addr,
                    "state": self.state,
                    # the mon fences THIS id if it replaces us
                    # while we are partitioned (_fence_mds)
                    "client": self.rados.client_id,
                    # ack: the subtree table epoch we have
                    # FLUSHED under (the export barrier)
                    "table_epoch": self._applied_table_epoch,
                }
                if self._adopted_ranks:
                    beacon["adopted_ranks"] = sorted(
                        list(e) for e in self._adopted_ranks
                    )
                rc, outb, _outs = self.rados.mon_command(beacon)
                if rc == 0 and outb:
                    told = json.loads(outb)
                    self.mdsmap_epoch = told.get("epoch", 0)
                    want = told.get("state", "standby")
                    self._peer_addrs = {
                        int(r): a
                        for r, a in told.get("actives", {}).items()
                    }
                    new_table = told.get("subtrees")
                    new_te = told.get("table_epoch", 0)
                    new_rank = told.get("rank", 0)
                    if self._adopted_ranks:
                        # the mon drained acked ranks from its stray
                        # queue: forget them, so a rank evicted AGAIN
                        # after a re-grow is re-adopted, not skipped
                        # (the gen tag already guarantees that; this
                        # just bounds the ack set)
                        still = {
                            (int(e[0]), int(e[1]))
                            for e in told.get("adopt_ranks", [])
                        }
                        self._adopted_ranks &= still
                    if want == "active" and (
                        self.state != "active"
                        or new_rank != self.rank
                    ):
                        # fresh promotion OR a mon-side rank
                        # reassignment (e.g. set-max-mds reshuffle):
                        # flush the old rank's state, then take over
                        # the new rank's journal
                        if self.state == "active":
                            with self._lock:
                                self._flush()
                        self._subtrees = dict(new_table or {"/": 0})
                        self._applied_table_epoch = new_te
                        self._become_active(new_rank)
                        if told.get("adopt_ranks"):
                            self._adopt_stray_ranks(
                                told["adopt_ranks"]
                            )
                    elif (
                        want == "active"
                        and told.get("adopt_ranks")
                    ):
                        # shrink adoption BEFORE acking the re-pinned
                        # table: the evicted rank's client-acked
                        # mutations must be in OUR cache/omap before
                        # clients route its subtrees here
                        self._adopt_stray_ranks(told["adopt_ranks"])
                        if (
                            new_table is not None
                            and new_te > self._applied_table_epoch
                        ):
                            self._apply_subtree_table(
                                new_table, new_te
                            )
                    elif (
                        want == "active"
                        and new_table is not None
                        and new_te > self._applied_table_epoch
                    ):
                        self._apply_subtree_table(new_table, new_te)
                    elif want != "active" and self.state == "active":
                        # demoted (mon promoted someone else while we
                        # were partitioned): stop serving immediately.
                        # Our old client id is blocklist-fenced — shed
                        # it for a fresh identity (the reference's
                        # respawn-with-new-addr) so a LATER promotion
                        # of this daemon can write again
                        self.state = "standby"
                        self.rados.objecter.new_identity()
            except Exception:  # noqa: BLE001 — beacons retry forever
                pass
            self._log_client.flush(self.rados.monc)

    def _become_active(self, rank: int = 0) -> None:
        """Standby takeover of a RANK: replay that rank's journal
        tail into the cache (the up:replay → up:active walk), then
        serve.  Each rank journals independently (MDLog is per-rank
        in the reference too), so replay rebuilds exactly the dead
        rank's unflushed mutations."""
        with self._lock:
            self.rank = rank
            self.journal = Journaler(
                self.meta,
                prefix=(
                    "mds_journal" if rank == 0
                    else f"mds_journal.{rank}"
                ),
            )
            self._dirs.clear()
            self._inodes.clear()
            self._dirty_dentries.clear()
            self._dirty_inodes.clear()
            self._removed_inodes.clear()
            self._mkfs_if_needed()
            self.journal.load()
            replayed = 0
            for blob in self.journal.replay():
                self._apply_entry(json.loads(blob))
                replayed += 1
            self.replayed_entries = replayed
            self._load_next_ino()
            self.state = "active"
            self.clog.info(
                f"mds.{self.name} is now active for rank {rank} "
                f"(replayed {replayed} journal entries)"
            )

    def _apply_subtree_table(self, table: dict, te: int) -> None:
        """Subtree table changed (a pin moved authority): flush ALL
        dirty state to the backing omap, drop the cache, and revoke
        every cap — the export/import handoff reduced to its
        essentials (the new auth loads lazily from the same backing
        objects, so migration IS the flush + table flip; the
        reference's Migrator streams cache state instead —
        deviation documented in the module docstring).  Only after
        this does the next beacon ack ``te``, which is what lets the
        mon expose the new table to clients."""
        with self._lock:
            self._flush()
            self._dirs.clear()
            self._inodes.clear()
            for ino in list(self._cap_holders):
                self._revoke(ino, None)
            self._subtrees = dict(table)
            self._applied_table_epoch = te

    def _adopt_stray_ranks(self, ranks) -> None:
        """Shrink adoption (``mds set-max-mds``): an evicted rank was
        FENCED mid-life, so its client-acked but unflushed mutations
        exist only in its per-rank journal.  Replay that journal into
        OUR cache (the ``mds fail`` takeover walk, but into the
        re-pin target instead of a promoted standby), flush to the
        backing omap, and trim the stray stream so a later re-grow
        promotion replays nothing stale.  ``ranks`` holds the mon's
        ``[rank, gen]`` queue entries; acked pair-for-pair on the
        next beacon (``adopted_ranks``) so the mon drains its queue
        and lets the re-pinned table stabilize for clients."""
        with self._lock:
            for rank, gen in sorted(
                (int(e[0]), int(e[1])) for e in ranks
            ):
                if (
                    (rank, gen) in self._adopted_ranks
                    or rank == self.rank
                ):
                    continue
                j = Journaler(
                    self.meta,
                    prefix=(
                        "mds_journal" if rank == 0
                        else f"mds_journal.{rank}"
                    ),
                )
                j.load()
                adopted = 0
                # allocations in the stray journal must advance the
                # EVICTED rank's persisted ino counter, not ours: a
                # re-grown rank resumes allocating from its own key,
                # and our counter must never jump into a foreign
                # range (disjoint per-rank ino spaces)
                saved_next = self._next_ino
                stray_max = -1
                for blob in j.replay():
                    ent = json.loads(blob)
                    self._apply_entry(ent)
                    if ent["op"] in ("mkdir", "create"):
                        ino = int(ent["ino"])
                        if (ino >> 40) == rank:
                            stray_max = max(stray_max, ino)
                    adopted += 1
                self._next_ino = max(
                    [saved_next]
                    + [
                        i + 1
                        for i in self._inodes
                        if self._my_ino(i)
                    ]
                )
                self._flush()
                if stray_max >= 0:
                    key = f"next_ino.{rank}"
                    stored = int(
                        self._ino_meta(ROOT_INO).get(
                            key, (rank << 40) + 2
                        )
                    )
                    nxt = max(stored, stray_max + 1)
                    self.meta.omap_set(
                        _ino_oid(ROOT_INO), {key: str(nxt).encode()}
                    )
                    self._inodes[ROOT_INO][key] = nxt
                j.trim()
                self._adopted_ranks.add((rank, gen))
                self.adopted_entries += adopted
                self.clog.info(
                    f"mds.{self.name} adopted rank {rank}'s journal "
                    f"({adopted} entries) after shrink"
                )

    # -- backing store (the ceph_tpu.fs omap layout) -----------------------
    def _mkfs_if_needed(self) -> None:
        from ..osdc.objecter import ObjectNotFound, RadosError

        try:
            self.meta.omap_get_vals(_ino_oid(ROOT_INO), max_return=1)
        except (ObjectNotFound, RadosError):
            self.meta.write_full(_ino_oid(ROOT_INO), b"")
            self.meta.omap_set(
                _ino_oid(ROOT_INO),
                {"type": b"dir", "next_ino": b"2"},
            )
            self.meta.write_full(_dir_oid(ROOT_INO), b"")

    # -- per-rank ino space ------------------------------------------------
    # ranks allocate from disjoint ranges (rank << 40 | counter) so
    # two actives never collide (the reference partitions via
    # per-rank inotable, src/mds/InoTable.cc); rank 0 keeps the
    # legacy low range.
    def _ino_key(self) -> str:
        return (
            "next_ino" if self.rank <= 0 else f"next_ino.{self.rank}"
        )

    def _ino_base(self) -> int:
        return 2 if self.rank <= 0 else (self.rank << 40) + 2

    def _my_ino(self, ino: int) -> bool:
        return (ino >> 40) == max(self.rank, 0)

    def _load_next_ino(self) -> None:
        stored = int(
            self._ino_meta(ROOT_INO).get(
                self._ino_key(), self._ino_base()
            )
        )
        # journal replay may carry allocations past the flushed value
        highest = max(
            [stored - 1]
            + [i for i in self._inodes if self._my_ino(i)]
            + [
                d["ino"]
                for frag in self._dirs.values()
                for d in frag.values()
                if self._my_ino(d["ino"])
            ]
        )
        self._next_ino = max(highest + 1, self._ino_base())

    def _load_dir(self, ino: int) -> dict[str, dict]:
        from ..osdc.objecter import ObjectNotFound, RadosError

        if ino not in self._dirs:
            try:
                vals = self.meta.omap_get_vals(_dir_oid(ino))
            except (ObjectNotFound, RadosError):
                raise KeyError(f"dirfrag {ino} missing")
            self._dirs[ino] = {
                k: json.loads(v) for k, v in vals.items()
            }
        return self._dirs[ino]

    def _ino_meta(self, ino: int) -> dict:
        from ..osdc.objecter import ObjectNotFound, RadosError

        if ino not in self._inodes:
            try:
                vals = self.meta.omap_get_vals(_ino_oid(ino))
            except (ObjectNotFound, RadosError):
                raise KeyError(f"inode {ino} missing")
            meta = {}
            for k, v in vals.items():
                v = v.decode()
                meta[k] = (
                    int(v)
                    if k in ("size",) or k.startswith("next_ino")
                    else v
                )
            self._inodes[ino] = meta
        return self._inodes[ino]

    def _flush(self) -> None:
        """Write dirty cache state to the backing omap and trim the
        journal (the MDLog expire / LogSegment flush role)."""
        for ino, dentries in self._dirty_dentries.items():
            sets = {
                name: json.dumps(d).encode()
                for name, d in dentries.items()
                if d is not None
            }
            rms = [name for name, d in dentries.items() if d is None]
            try:
                self.meta.stat(_dir_oid(ino))
            except Exception:  # noqa: BLE001 — create the frag object
                self.meta.write_full(_dir_oid(ino), b"")
            if sets:
                self.meta.omap_set(_dir_oid(ino), sets)
            if rms:
                self.meta.omap_rm_keys(_dir_oid(ino), rms)
        for ino in self._dirty_inodes:
            if ino in self._removed_inodes:
                continue
            meta = self._inodes.get(ino, {})
            try:
                self.meta.stat(_ino_oid(ino))
            except Exception:  # noqa: BLE001
                self.meta.write_full(_ino_oid(ino), b"")
            self.meta.omap_set(
                _ino_oid(ino),
                {
                    k: str(v).encode()
                    for k, v in meta.items()
                },
            )
        for ino in self._removed_inodes:
            for oid in (_ino_oid(ino), _dir_oid(ino)):
                try:
                    self.meta.remove(oid)
                except Exception:  # noqa: BLE001
                    pass
        self.meta.omap_set(
            _ino_oid(ROOT_INO),
            {self._ino_key(): str(self._next_ino).encode()},
        )
        self._dirty_dentries.clear()
        self._dirty_inodes.clear()
        self._removed_inodes.clear()
        self._unflushed = 0
        self.journal.trim()

    # -- journal apply (shared by live ops and replay) ---------------------
    def _apply_entry(self, ent: dict) -> None:
        """Apply one EUpdate-style record to the cache.  Replay must
        be idempotent: records carry every allocated ino."""
        op = ent["op"]
        if op in ("mkdir", "create"):
            parent, name, ino = ent["parent"], ent["name"], ent["ino"]
            frag = self._load_dir_or_empty(parent)
            typ = "dir" if op == "mkdir" else "file"
            frag[name] = {"type": typ, "ino": ino}
            self._mark_dentry(parent, name, frag[name])
            meta = {"type": typ, "mtime": ent["mtime"]}
            if op == "create":
                meta["size"] = 0
            else:
                self._dirs.setdefault(ino, {})
            self._inodes[ino] = meta
            self._dirty_inodes.add(ino)
            self._removed_inodes.discard(ino)
            self._next_ino = max(self._next_ino, ino + 1)
        elif op in ("rmdir", "unlink"):
            parent, name, ino = ent["parent"], ent["name"], ent["ino"]
            frag = self._load_dir_or_empty(parent)
            frag.pop(name, None)
            self._mark_dentry(parent, name, None)
            self._inodes.pop(ino, None)
            self._dirs.pop(ino, None)
            self._removed_inodes.add(ino)
            self._dirty_inodes.discard(ino)
        elif op == "rename":
            sp, sn = ent["sparent"], ent["sname"]
            dp, dn = ent["dparent"], ent["dname"]
            dentry = ent["dentry"]
            self._load_dir_or_empty(sp).pop(sn, None)
            self._mark_dentry(sp, sn, None)
            self._load_dir_or_empty(dp)[dn] = dentry
            self._mark_dentry(dp, dn, dentry)
        elif op == "rename_out":
            # OUR half of a cross-rank rename: the dentry leaves
            # this rank's subtree (the peer journals the insert).
            # Drop the cached inode meta too — the new auth owns it
            # now, and a later rename BACK must reload its (possibly
            # mutated) meta from the backing omap, not trust ours.
            parent, name = ent["parent"], ent["name"]
            frag = self._load_dir_or_empty(parent)
            gone = frag.pop(name, None)
            self._mark_dentry(parent, name, None)
            if gone is not None:
                self._inodes.pop(gone["ino"], None)
                self._dirty_inodes.discard(gone["ino"])
        elif op == "rename_in":
            parent, name = ent["parent"], ent["name"]
            dentry = ent["dentry"]
            self._load_dir_or_empty(parent)[name] = dentry
            self._mark_dentry(parent, name, dentry)
            # force a lazy reload of the arriving inode's meta (the
            # old auth flushed it before the peer_link)
            self._inodes.pop(dentry["ino"], None)
        elif op == "setattr":
            ino = ent["ino"]
            try:
                meta = self._ino_meta(ino)
            except KeyError:
                meta = self._inodes.setdefault(ino, {})
            meta.update(ent["attrs"])
            self._dirty_inodes.add(ino)
        else:
            raise ValueError(f"unknown journal op {op!r}")

    def _load_dir_or_empty(self, ino: int) -> dict[str, dict]:
        try:
            return self._load_dir(ino)
        except KeyError:
            return self._dirs.setdefault(ino, {})

    def _mark_dentry(self, dir_ino, name, dentry) -> None:
        self._dirty_dentries.setdefault(dir_ino, {})[name] = dentry

    def _journal_and_apply(
        self, ent: dict, force_flush: bool = False
    ) -> None:
        self.journal.append(json.dumps(ent).encode())
        self.journal.flush()
        self._apply_entry(ent)
        self._unflushed += 1
        if force_flush or self._unflushed >= self.flush_every:
            self._flush()

    # -- subtree authority (MDCache subtree auth, reduced) -----------------
    def _auth_rank(self, path: str) -> int:
        from . import subtree_auth_rank

        return subtree_auth_rank(self._subtrees, path)

    def _check_auth(self, path: str) -> None:
        r = self._auth_rank(path)
        if r != self.rank:
            # the client re-routes from the hinted rank (the
            # reference's MDS would forward the request itself;
            # client-side re-dispatch is the reduction)
            raise _Err(
                -116,
                f"not auth for {path!r}; mds rank {r} is (-ESTALE "
                f"auth={r})",
            )

    def _is_boundary(self, dir_path: str) -> bool:
        """A dirfrag some pin path passes THROUGH: its dentries are
        walked by other ranks, so mutations flush immediately (other
        ranks read boundary frags fresh from the backing omap — see
        _walk).  Non-boundary frags keep the lazy-flush + journal
        discipline."""
        parts = [p for p in dir_path.split("/") if p]
        for pref in self._subtrees:
            pp = [x for x in pref.split("/") if x]
            if len(pp) > len(parts) and pp[: len(parts)] == parts:
                return True
        return False

    @staticmethod
    def _dirname(path: str) -> str:
        from . import path_dirname

        return path_dirname(path)

    def _read_dir_fresh(self, ino: int) -> dict[str, dict]:
        """Uncached read of a FOREIGN dirfrag: another rank owns (and
        may be mutating) it; caching would go stale with no recall
        path.  Boundary frags flush-on-mutate at their auth, so this
        read is coherent up to the op in flight."""
        from ..osdc.objecter import ObjectNotFound, RadosError

        try:
            vals = self.meta.omap_get_vals(_dir_oid(ino))
        except (ObjectNotFound, RadosError):
            return {}
        return {k: json.loads(v) for k, v in vals.items()}

    # -- path walking ------------------------------------------------------
    def _walk(self, path: str) -> tuple[int, dict]:
        parts = [p for p in path.split("/") if p]
        ino = ROOT_INO
        dentry = {"type": "dir", "ino": ROOT_INO}
        for i, name in enumerate(parts):
            if dentry["type"] != "dir":
                raise _Err(-20, f"{name!r}: not a directory (-ENOTDIR)")
            prefix = "/" + "/".join(parts[:i])
            if self._auth_rank(prefix) == self.rank:
                frag = self._load_dir_or_empty(ino)
            else:
                frag = self._read_dir_fresh(ino)
            if name not in frag:
                raise _Err(-2, f"{path!r} (-ENOENT)")
            dentry = frag[name]
            ino = dentry["ino"]
        return ino, dentry

    def _parent_of(self, path: str) -> tuple[int, str]:
        parts = [p for p in path.split("/") if p]
        if not parts:
            raise _Err(-22, "root has no parent (-EINVAL)")
        ino, dentry = self._walk("/".join(parts[:-1]))
        if dentry["type"] != "dir":
            raise _Err(-20, "not a directory (-ENOTDIR)")
        return ino, parts[-1]

    # -- capabilities (Locker role) ----------------------------------------
    def _grant(self, session: _Session, ino: int) -> None:
        session.caps.add(ino)
        self._cap_holders.setdefault(ino, set()).add(session)

    def _revoke(self, ino: int, requester: _Session | None) -> None:
        """Recall every OTHER session's cap on ``ino`` and wait for
        the acks — the mutation must not commit while a peer still
        trusts its cache (Locker::issue_caps / revoke flow)."""
        holders = self._cap_holders.get(ino)
        if not holders:
            return
        for sess in list(holders):
            if sess is requester:
                continue
            try:
                ack = sess.conn.call(
                    MClientCaps(action="revoke", ino=ino), timeout=5.0
                )
                if (
                    not isinstance(ack, MClientCaps)
                    or ack.action != "ack"
                ):
                    raise MessageError("bad cap ack")
            except (MessageError, OSError):
                # dead client: drop the whole session (its caps die
                # with it), exactly so one hung client cannot wedge
                # the namespace
                self._drop_session(sess)
            holders.discard(sess)
            sess.caps.discard(ino)
        if not self._cap_holders.get(ino):
            self._cap_holders.pop(ino, None)

    def _drop_session(self, sess: _Session) -> None:
        for ino in sess.caps:
            holders = self._cap_holders.get(ino)
            if holders:
                holders.discard(sess)
        sess.caps.clear()
        self._sessions.pop(sess.conn, None)

    # -- dispatch ----------------------------------------------------------
    def ms_dispatch(self, conn: Connection, msg) -> bool:
        if not isinstance(msg, MClientRequest):
            return False
        if self.shared_services:
            self._work_strand.submit(
                lambda: self._work_one((conn, msg))
            )
        else:
            self._workq.put((conn, msg))
        return True

    def _work_loop(self) -> None:
        while True:
            item = self._workq.get()
            if item is None:
                return
            self._work_one(item)

    def _work_one(self, item) -> None:
        if self._stop.is_set():
            return
        try:
            self._process(*item)
        except Exception as e:  # noqa: BLE001 — the worker
            # survives; the dead op files a crash report
            import traceback

            traceback.print_exc()
            crash_util.capture(
                f"mds.{self.name}", e, clog=self.clog
            )

    def _process(self, conn: Connection, msg: MClientRequest) -> None:
        reply = MClientReply(tid=msg.tid)
        try:
            with self._lock:
                if msg.op == "open_session":
                    args = json.loads(msg.args)
                    self._sessions[conn] = _Session(
                        conn, args.get("name", "")
                    )
                    reply.outb = json.dumps({"state": self.state})
                elif self.state != "active":
                    reply.rc = -11
                    reply.outs = "mds not active (-EAGAIN)"
                elif msg.op.startswith("peer_"):
                    # MDS→MDS sub-op (the slave-request seat): no
                    # client session — the caller is a peer rank
                    outb = self._handle_peer(
                        msg.op, json.loads(msg.args)
                    )
                    reply.outb = json.dumps(outb)
                else:
                    sess = self._sessions.get(conn)
                    if sess is None:
                        reply.rc = -1
                        reply.outs = "no session (-EPERM)"
                    elif msg.reqid and msg.reqid in sess.replies:
                        rc, outs, outb = sess.replies[msg.reqid]
                        reply.rc, reply.outs, reply.outb = rc, outs, outb
                    else:
                        outb = self._handle_op(
                            sess, msg.op, json.loads(msg.args)
                        )
                        reply.outb = json.dumps(outb)
                        if msg.reqid:
                            sess.replies[msg.reqid] = (
                                0, "", reply.outb,
                            )
                            while len(sess.replies) > 128:
                                sess.replies.pop(
                                    next(iter(sess.replies))
                                )
        except _Err as e:
            reply.rc, reply.outs = e.rc, str(e)
        except Exception as e:  # noqa: BLE001 — the RPC contract: an
            # op must always produce a reply
            reply.rc = -5
            reply.outs = f"{type(e).__name__}: {e}"
        try:
            conn.send(reply)
        except (MessageError, OSError):
            pass

    def ms_handle_reset(self, conn: Connection) -> None:
        with self._lock:
            sess = self._sessions.get(conn)
            if sess is not None:
                self._drop_session(sess)

    # -- ops (Server.cc handle_client_* reduced) ---------------------------
    def _handle_op(self, sess: _Session, op: str, args: dict) -> dict:
        self.ops_served += 1
        if op == "mkdir":
            self._check_auth(self._dirname(args["path"]))
            parent, name = self._parent_of(args["path"])
            if name in self._load_dir_or_empty(parent):
                raise _Err(-17, f"{args['path']!r} exists (-EEXIST)")
            self._revoke(parent, sess)
            ino = self._next_ino
            self._next_ino += 1
            self._journal_and_apply(
                {
                    "op": "mkdir", "parent": parent, "name": name,
                    "ino": ino, "mtime": time.time(),
                },
                force_flush=self._is_boundary(
                    self._dirname(args["path"])
                ),
            )
            return {"ino": ino}
        if op == "create":
            self._check_auth(self._dirname(args["path"]))
            parent, name = self._parent_of(args["path"])
            if name in self._load_dir_or_empty(parent):
                raise _Err(-17, f"{args['path']!r} exists (-EEXIST)")
            self._revoke(parent, sess)
            ino = self._next_ino
            self._next_ino += 1
            self._journal_and_apply(
                {
                    "op": "create", "parent": parent, "name": name,
                    "ino": ino, "mtime": time.time(),
                },
                force_flush=self._is_boundary(
                    self._dirname(args["path"])
                ),
            )
            return {"ino": ino}
        if op == "rmdir":
            self._check_auth(self._dirname(args["path"]))
            parent, name = self._parent_of(args["path"])
            frag = self._load_dir_or_empty(parent)
            if name not in frag:
                raise _Err(-2, f"{args['path']!r} (-ENOENT)")
            dentry = frag[name]
            if dentry["type"] != "dir":
                raise _Err(-20, "not a directory (-ENOTDIR)")
            if self._load_dir_or_empty(dentry["ino"]):
                raise _Err(-39, "not empty (-ENOTEMPTY)")
            self._revoke(parent, sess)
            self._revoke(dentry["ino"], sess)
            self._journal_and_apply(
                {
                    "op": "rmdir", "parent": parent, "name": name,
                    "ino": dentry["ino"],
                },
                force_flush=self._is_boundary(
                    self._dirname(args["path"])
                ),
            )
            return {}
        if op == "unlink":
            self._check_auth(self._dirname(args["path"]))
            parent, name = self._parent_of(args["path"])
            frag = self._load_dir_or_empty(parent)
            if name not in frag:
                raise _Err(-2, f"{args['path']!r} (-ENOENT)")
            dentry = frag[name]
            if dentry["type"] == "dir":
                raise _Err(-21, "is a directory (-EISDIR)")
            self._revoke(parent, sess)
            self._revoke(dentry["ino"], sess)
            self._journal_and_apply(
                {
                    "op": "unlink", "parent": parent, "name": name,
                    "ino": dentry["ino"],
                },
                force_flush=self._is_boundary(
                    self._dirname(args["path"])
                ),
            )
            return {"ino": dentry["ino"]}
        if op == "rename":
            src_dir = self._dirname(args["src"])
            dst_dir = self._dirname(args["dst"])
            self._check_auth(src_dir)
            sp, sn = self._parent_of(args["src"])
            sfrag = self._load_dir_or_empty(sp)
            if sn not in sfrag:
                raise _Err(-2, f"{args['src']!r} (-ENOENT)")
            dst_rank = self._auth_rank(dst_dir)
            if dst_rank != self.rank:
                # cross-subtree rename: the dst auth journals the
                # link (our "slave request", Migrator/Server
                # rename-across-auth reduced to link-then-unlink; a
                # crash between the two leaves the name visible in
                # BOTH places — never lost).  Flush FIRST: the moved
                # inode's dirty meta (size/mtime) must reach the
                # backing omap before the new auth loads it lazily —
                # the same export barrier a pin flip uses.
                self._flush()
                self._peer_call(
                    dst_rank, "peer_link",
                    {"dst": args["dst"], "dentry": sfrag[sn]},
                )
                self._revoke(sp, sess)
                self._journal_and_apply(
                    {"op": "rename_out", "parent": sp, "name": sn},
                    force_flush=self._is_boundary(src_dir),
                )
                return {}
            dp, dn = self._parent_of(args["dst"])
            if dn in self._load_dir_or_empty(dp):
                raise _Err(-17, f"{args['dst']!r} exists (-EEXIST)")
            self._revoke(sp, sess)
            self._revoke(dp, sess)
            self._journal_and_apply(
                {
                    "op": "rename", "sparent": sp, "sname": sn,
                    "dparent": dp, "dname": dn,
                    "dentry": sfrag[sn],
                },
                force_flush=(
                    self._is_boundary(src_dir)
                    or self._is_boundary(dst_dir)
                ),
            )
            return {}
        if op == "readdir":
            self._check_auth(args["path"])
            ino, dentry = self._walk(args["path"])
            if dentry["type"] != "dir":
                raise _Err(-20, "not a directory (-ENOTDIR)")
            self._grant(sess, ino)
            return {
                "ino": ino,
                "entries": self._load_dir_or_empty(ino),
            }
        if op == "stat":
            self._check_auth(args["path"])
            ino, dentry = self._walk(args["path"])
            try:
                meta = self._ino_meta(ino)
            except KeyError:
                meta = {}
            self._grant(sess, ino)
            return {
                "ino": ino,
                "type": dentry["type"],
                "size": int(meta.get("size", 0)),
                "mtime": float(meta.get("mtime", 0)),
            }
        if op == "setattr":
            self._check_auth(args["path"])
            ino, dentry = self._walk(args["path"])
            attrs = dict(args["attrs"])
            if args.get("grow_only") and "size" in attrs:
                try:
                    cur = int(self._ino_meta(ino).get("size", 0))
                except KeyError:
                    cur = 0
                attrs["size"] = max(cur, int(attrs["size"]))
            self._revoke(ino, sess)
            self._journal_and_apply(
                {"op": "setattr", "ino": ino, "attrs": attrs}
            )
            return {"ino": ino, "size": attrs.get("size")}
        raise _Err(-22, f"unknown op {op!r} (-EINVAL)")

    # -- MDS-to-MDS sub-ops (slave requests, reduced) ----------------------
    def _peer_call(self, rank: int, op: str, args: dict) -> dict:
        """Blocking sub-op on a peer active.  Runs on the worker
        thread (never the messenger loop — connect/call would
        deadlock there).  A timeout surfaces as -EAGAIN so the
        client retries the whole op; two opposite-direction
        cross-renames can in principle wait on each other's worker,
        and the timeout is what unwinds that (the reference orders
        slave requests by MDRequest instead)."""
        addr = self._peer_addrs.get(rank)
        if addr is None:
            raise _Err(-11, f"no active mds rank {rank} (-EAGAIN)")
        try:
            conn = self._peer_conns.get(rank)
            if conn is None or conn.is_closed:
                host, _, port = addr.rpartition(":")
                conn = self.msgr.connect(host, int(port))
                self._peer_conns[rank] = conn
            from ..msg.message import MClientRequest as _Req

            reply = conn.call(
                _Req(op=op, args=json.dumps(args)), timeout=5.0
            )
        except (MessageError, OSError) as e:
            self._peer_conns.pop(rank, None)
            raise _Err(-11, f"peer rank {rank} unreachable: {e} (-EAGAIN)")
        if reply.rc != 0:
            raise _Err(reply.rc, reply.outs)
        return json.loads(reply.outb) if reply.outb else {}

    def _handle_peer(self, op: str, args: dict) -> dict:
        if op == "peer_link":
            dst = args["dst"]
            self._check_auth(self._dirname(dst))
            dp, dn = self._parent_of(dst)
            existing = self._load_dir_or_empty(dp).get(dn)
            if existing is not None:
                if existing.get("ino") == args["dentry"].get("ino"):
                    # retried cross-rename whose first attempt
                    # already linked here: idempotent success (the
                    # ack was lost, not the commit)
                    return {}
                raise _Err(-17, f"{dst!r} exists (-EEXIST)")
            self._revoke(dp, None)
            self._journal_and_apply(
                {
                    "op": "rename_in", "parent": dp, "name": dn,
                    "dentry": args["dentry"],
                },
                force_flush=self._is_boundary(self._dirname(dst)),
            )
            return {}
        raise _Err(-22, f"unknown peer op {op!r} (-EINVAL)")


class _Err(Exception):
    def __init__(self, rc: int, msg: str):
        super().__init__(msg)
        self.rc = rc
