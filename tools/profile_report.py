#!/usr/bin/env python
"""Render a bench artifact's device-dispatch breakdown as per-kind
text tables, so a BENCH_rNN diff is human-readable instead of a JSON
stare (``python tools/profile_report.py BENCH_r06.json``).

The flight recorder (ops/profiler.py) attributes every device
dispatch's wall time to transfer/compute/sync and carries batch
occupancy, pad waste from pow2 shape bucketing, and the
uploaded-vs-resident byte split; bench.py embeds one breakdown dict
per device section (``e2e_batched``/``recovery``/``ec_families``/
``crush``).  This tool finds every embedded breakdown in an artifact
(any depth — the layout may grow) and prints one table per section:

    section: e2e_batched  [backend=jax-tpu]
    kind        disp   occ  transfer  compute     sync  pad%  res%
    ec_encode     20  12.4    42.1ms   18.3ms    3.2ms   0.5  78.2
    ...

Reads stdin when no path is given, so it composes with shell diffs:
``jq .e2e_batched BENCH_r06.json | python tools/profile_report.py``.
"""

from __future__ import annotations

import json
import sys

# the six contract keys every breakdown dict carries (bench satellite:
# they must emit on the tunnel-down CPU path too)
BREAKDOWN_KEYS = (
    "transfer_ms", "compute_ms", "sync_ms",
    "occupancy", "pad_waste_ratio", "resident_byte_ratio",
)

_COLS = (
    ("kind", 12), ("disp", 6), ("occ", 7), ("stripes/d", 10),
    ("transfer", 11), ("compute", 11), ("sync", 11),
    ("pad%", 7), ("res%", 7), ("hit%", 7),
)


def is_breakdown(node) -> bool:
    return isinstance(node, dict) and all(
        k in node for k in BREAKDOWN_KEYS
    )


def find_breakdowns(node, path="") -> list[tuple[str, dict]]:
    """Every embedded breakdown dict in the artifact, with its JSON
    path — depth-first so section order matches the file."""
    found: list[tuple[str, dict]] = []
    if is_breakdown(node):
        return [(path or "(root)", node)]
    if isinstance(node, dict):
        for k, v in node.items():
            found.extend(find_breakdowns(v, f"{path}.{k}" if path else k))
    elif isinstance(node, list):
        for i, v in enumerate(node):
            found.extend(find_breakdowns(v, f"{path}[{i}]"))
    return found


def _fmt_ms(v: float) -> str:
    return f"{float(v):.3f}ms"


def _fmt_pct(v: float) -> str:
    return f"{100.0 * float(v):.1f}"


def _row(cells) -> str:
    return "  ".join(
        str(c).ljust(w) if i == 0 else str(c).rjust(w)
        for i, ((_n, w), c) in enumerate(zip(_COLS, cells))
    ).rstrip()


def _kind_cells(name: str, d: dict) -> list[str]:
    lookups = d.get("compile_hits", 0) + d.get("compile_misses", 0)
    return [
        name,
        d.get("dispatches", 0),
        f"{float(d.get('occupancy', 0.0)):.1f}",
        f"{float(d.get('stripes_per_dispatch', 0.0)):.1f}",
        _fmt_ms(d.get("transfer_ms", 0.0)),
        _fmt_ms(d.get("compute_ms", 0.0)),
        _fmt_ms(d.get("sync_ms", 0.0)),
        _fmt_pct(d.get("pad_waste_ratio", 0.0)),
        _fmt_pct(d.get("resident_byte_ratio", 0.0)),
        (
            _fmt_pct(d.get("compile_hits", 0) / lookups)
            if lookups
            else "-"
        ),
    ]


def render_breakdown(path: str, bd: dict) -> str:
    lines = [
        f"section: {path}  [backend={bd.get('backend', '?')}]"
    ]
    header = _row([name for name, _w in _COLS])
    lines.append(header)
    lines.append("-" * len(header))
    kinds = bd.get("kinds") or {}
    for kind in sorted(kinds):
        lines.append(_row(_kind_cells(kind, kinds[kind])))
    if not kinds:
        lines.append("(no device dispatches recorded)")
    else:
        lines.append(_row(_kind_cells("TOTAL", bd)))
    return "\n".join(lines)


def render(artifact: dict) -> str:
    """The whole artifact → one table per embedded breakdown (empty
    string when the artifact predates the flight recorder)."""
    parts = [
        render_breakdown(path, bd)
        for path, bd in find_breakdowns(artifact)
    ]
    return "\n\n".join(parts)


def main(argv) -> int:
    if len(argv) > 1:
        with open(argv[1]) as f:
            artifact = json.load(f)
    else:
        artifact = json.load(sys.stdin)
    text = render(artifact)
    if not text:
        print(
            "profile_report: no dispatch breakdowns in this artifact "
            "(pre-flight-recorder bench?)",
            file=sys.stderr,
        )
        return 1
    print(text)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
