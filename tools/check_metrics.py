#!/usr/bin/env python
"""Metrics-schema lint — walk every registered PerfCounters schema
and fail on exporter-breaking declarations (run in tier-1 via
tests/test_observability.py, and standalone as
``python tools/check_metrics.py``).

Checks, per counter set:

- duplicate counter names within a set (the builder asserts at
  declaration time; dynamically-extended sets — KernelStats — can
  bypass it) and duplicate (set, counter) pairs across sets after the
  exporter's name transformation;
- names that the Prometheus exposition format rejects: anything
  outside ``[a-zA-Z_:][a-zA-Z0-9_:]*`` AFTER the mgr exporter's
  sanitization would silently collide or be dropped — the lint flags
  the raw name so the collision is fixed at the source;
- histogram counters with no bucket bounds (an unbounded histogram
  dumps an empty bucket array and renders as a zero-information
  series).

The walked schemas are the product's real ones: the OSD daemon's
counter block, the batched-mapping counters, and the device-kernel
telemetry plane (after forcing registration of every group).

The event-plane schemas are linted the same way: a real clog entry
(common/log_client.py) and a real crash report (common/crash.py) are
generated and checked for required fields, bounded sizes, and
label-safe values — the shapes the mon LogStore, the mgr crash
module, and the prometheus exporter all assume.
"""

from __future__ import annotations

import re
import sys

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")

# -- event-plane schema bounds ---------------------------------------------
CLOG_REQUIRED = ("name", "stamp", "channel", "prio", "message", "seq")
CLOG_PRIOS = {"debug", "info", "warn", "error", "sec"}
CLOG_MAX_MESSAGE = 4096
CLOG_MAX_CHANNEL = 64
CLOG_MAX_NAME = 64
# channels/names become Prometheus label values and CLI columns:
# printable, no control characters
_LABEL_SAFE_RE = re.compile(r"^[\x20-\x7e]*$")
_CHANNEL_RE = re.compile(r"^[a-zA-Z][a-zA-Z0-9_.-]*$")

# -- scrub-plane schema bounds ----------------------------------------------
# inconsistency records (osd/scrub.py make_record, the rados
# list-inconsistent-obj shape served by the primary's ScrubStore)
INCONSISTENT_REQUIRED = (
    "object", "errors", "union_shard_errors", "shards", "oid",
)
INCONSISTENT_MAX_SHARDS = 64
INCONSISTENT_MAX_NAME = 1024
# scrub counters the OSD schema must declare (the mgr exporter's
# ceph_osd_scrub_* families read exactly these)
SCRUB_COUNTERS = (
    "scrub_errors", "scrubs_active", "scrub_chunks",
    "scrub_deep_bytes", "scrub_last_age",
)

# fault-injection counters every messenger schema must declare
# (msg/faults.py build_msgr_perf → the ceph_msgr_fault_* families)
FAULT_COUNTERS = (
    "fault_dropped", "fault_delayed", "fault_duplicated",
    "fault_socket_failures",
)
# shared-stack worker telemetry the stack schema must declare
# (msg/stack.py build_stack_perf — aggregates plus the per-worker
# series, all riding stack_perf_dump() → MMgrReport → prometheus)
WORKER_COUNTERS = (
    "l_msgr_workers",
    "l_msgr_worker_connections",
    "l_msgr_worker_dispatch",
    "l_msgr_worker_loop_lag",
    "l_msgr_offload_threads",
    "l_msgr_offload_threads_peak",
)
WORKER_PER_INDEX_COUNTERS = (
    "l_msgr_worker{i}_connections",
    "l_msgr_worker{i}_dispatch",
    "l_msgr_worker{i}_loop_lag",
)
# fullness gauges the OSD schema must declare (the osd_stat_t carry
# feeding OSD_NEARFULL/OSD_FULL and the backoff visibility gauge)
FULLNESS_COUNTERS = (
    "stat_bytes", "stat_bytes_used", "stat_bytes_avail",
    "backoffs_active",
)
# device-residency + coalesced-encode families the kernel-stats
# schema must declare (ops/residency.py ensure_counters — the
# data-plane batching observability the e2e_batched bench reads)
RESIDENCY_COUNTERS = (
    "l_tpu_residency_hits",
    "l_tpu_residency_misses",
    "l_tpu_residency_evictions",
    "l_tpu_residency_bytes_resident",
    "l_tpu_batch_encode_dispatches",
    "l_tpu_batch_encode_ops_per_dispatch",
    "l_tpu_batch_decode_dispatches",
    "l_tpu_batch_decode_ops_per_dispatch",
)
# device-dispatch flight-recorder family the kernel-stats schema must
# declare (ops/profiler.py ensure_dispatch_counters — the
# transfer/compute/sync attribution plane the bench breakdown and the
# `dispatch history|summary` tell surface read), plus the pad-waste
# counter kernel_stats registers at construction
DISPATCH_COUNTERS = (
    "l_tpu_dispatch_count",
    "l_tpu_dispatch_ops",
    "l_tpu_dispatch_stripes",
    "l_tpu_dispatch_bytes_uploaded",
    "l_tpu_dispatch_bytes_resident",
    "l_tpu_dispatch_ring_dropped",
    "l_tpu_dispatch_transfer_lat",
    "l_tpu_dispatch_transfer_lat_hist",
    "l_tpu_dispatch_compute_lat",
    "l_tpu_dispatch_compute_lat_hist",
    "l_tpu_dispatch_sync_lat",
    "l_tpu_dispatch_sync_lat_hist",
    "l_tpu_pad_bytes_wasted",
)
# sharded bucket-index + reshard families the RGW schema must
# declare (rgw/index.py build_rgw_perf — the bench rgw_index section
# and the reshard-under-load tests read exactly these)
RGW_INDEX_COUNTERS = (
    "l_rgw_index_ops",
    "l_rgw_index_reads",
    "l_rgw_index_list_pages",
    "l_rgw_index_list_entries",
    "l_rgw_index_retries",
    "l_rgw_index_dual_writes",
    "l_rgw_index_stall_waits",
    "l_rgw_index_shards",
    "l_rgw_reshard_queued",
    "l_rgw_reshard_started",
    "l_rgw_reshard_completed",
    "l_rgw_reshard_entries_migrated",
    "l_rgw_reshard_passes",
    "l_rgw_reshard_in_progress",
)
# WAL-plane counters the wal_store schema must declare
# (store/wal_store.py build_wal_perf — the bench wal section, the
# chaos kill-storm verdict, and the mgr exporter read exactly these)
WAL_COUNTERS = (
    "l_os_wal_appends",
    "l_os_wal_append_bytes",
    "l_os_wal_deferred",
    "l_os_wal_deferred_bytes",
    "l_os_wal_barriers",
    "l_os_wal_group_records",
    "l_os_wal_barrier_waits",
    "l_os_wal_reads_from_log",
    "l_os_wal_applies",
    "l_os_wal_apply_errors",
    "l_os_wal_replay_records",
    "l_os_wal_checkpoints",
    "l_os_wal_pending_records",
    "l_os_wal_pending_bytes",
)
# process-runtime counters the supervisor schema must declare
# (proc/supervisor.py build_proc_perf — the respawn/crash-loop
# telemetry riding MMgrReport like every daemon's), and the dispatch
# backpressure pair the STACK schema must declare (msg/stack.py
# build_stack_perf — depth gauge + stall counter the bounded inbound
# queue maintains)
PROC_COUNTERS = (
    "l_proc_children",
    "l_proc_restarts",
    "l_proc_crash_loops",
)
# qa thrasher counters (qa/thrasher.py build_thrash_perf): the chaos
# smoke gate's event/violation/shrink accounting
THRASH_COUNTERS = (
    "l_thrash_events",
    "l_thrash_skipped_events",
    "l_thrash_violations",
    "l_thrash_shrink_steps",
)
# client op-path counters (osdc/objecter.py build_objecter_perf):
# the backoff-park visibility the full-OSD scenarios read
OBJECTER_COUNTERS = (
    "l_objecter_backoff_parks",
)
DISPATCH_QUEUE_COUNTERS = (
    "l_msgr_dispatch_queue_depth",
    "l_msgr_dispatch_queue_stalls",
)
# recovery-storm counters the OSD schema must declare (the
# l_osd_recovery_* block: batched decode rebuild progress + the
# survivor-read fan-in the LRC locality claim is measured from)
RECOVERY_COUNTERS = (
    "recovery_active",
    "recovery_pushes",
    "recovery_push_bytes",
    "recovery_batches",
    "recovery_batch_ops",
    "recovery_survivor_shards",
    "recovery_helper_bytes",
)

CRASH_REQUIRED = (
    "crash_id", "entity_name", "timestamp", "timestamp_iso",
    "exception", "backtrace", "dout_tail", "meta",
)
CRASH_ID_RE = re.compile(
    r"^\d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}\.\d{6}Z_[0-9a-f-]{36}$"
)
CRASH_MAX_BACKTRACE_LINES = 100
CRASH_MAX_LINE = 2048
CRASH_MAX_DOUT_TAIL = 200


def check_clog_entry(entry) -> list[str]:
    """Lint one cluster-log entry (LogClient/MLog/LogStore shape)."""
    errors: list[str] = []
    if not isinstance(entry, dict):
        return ["clog entry: not a dict"]
    for field in CLOG_REQUIRED:
        if field not in entry:
            errors.append(f"clog entry: missing field {field!r}")
    prio = entry.get("prio")
    if prio is not None and prio not in CLOG_PRIOS:
        errors.append(f"clog entry: unknown prio {prio!r}")
    channel = str(entry.get("channel", ""))
    if len(channel) > CLOG_MAX_CHANNEL or not _CHANNEL_RE.match(
        channel or "-"
    ):
        errors.append(
            f"clog entry: channel {channel!r} unbounded or not "
            "label-safe"
        )
    name = str(entry.get("name", ""))
    if len(name) > CLOG_MAX_NAME or not _LABEL_SAFE_RE.match(name):
        errors.append(
            f"clog entry: name {name!r} unbounded or not label-safe"
        )
    message = entry.get("message", "")
    if not isinstance(message, str) or len(message) > CLOG_MAX_MESSAGE:
        errors.append("clog entry: message missing, non-str, or over "
                      f"{CLOG_MAX_MESSAGE} bytes")
    if not isinstance(entry.get("stamp", 0.0), (int, float)):
        errors.append("clog entry: stamp is not a number")
    if not isinstance(entry.get("seq", 0), int):
        errors.append("clog entry: seq is not an int")
    return errors


def check_crash_report(report) -> list[str]:
    """Lint one crash report (common/crash.py / mgr crash shape)."""
    errors: list[str] = []
    if not isinstance(report, dict):
        return ["crash report: not a dict"]
    for field in CRASH_REQUIRED:
        if field not in report:
            errors.append(f"crash report: missing field {field!r}")
    cid = str(report.get("crash_id", ""))
    if not CRASH_ID_RE.match(cid):
        errors.append(
            f"crash report: crash_id {cid!r} not <ISO stamp>_<uuid>"
        )
    entity = str(report.get("entity_name", ""))
    if len(entity) > CLOG_MAX_NAME or not _LABEL_SAFE_RE.match(entity):
        errors.append(
            f"crash report: entity_name {entity!r} unbounded or not "
            "label-safe"
        )
    bt = report.get("backtrace", [])
    if not isinstance(bt, list) or not all(
        isinstance(ln, str) for ln in bt
    ):
        errors.append("crash report: backtrace is not a list of str")
    else:
        if len(bt) > CRASH_MAX_BACKTRACE_LINES:
            errors.append(
                f"crash report: backtrace over "
                f"{CRASH_MAX_BACKTRACE_LINES} lines"
            )
        if any(len(ln) > CRASH_MAX_LINE for ln in bt):
            errors.append(
                f"crash report: backtrace line over {CRASH_MAX_LINE}"
            )
    tail = report.get("dout_tail", [])
    if not isinstance(tail, list) or len(tail) > CRASH_MAX_DOUT_TAIL:
        errors.append(
            f"crash report: dout_tail missing, non-list, or over "
            f"{CRASH_MAX_DOUT_TAIL} entries"
        )
    if not isinstance(report.get("timestamp", 0.0), (int, float)):
        errors.append("crash report: timestamp is not a number")
    if not isinstance(report.get("meta", {}), dict):
        errors.append("crash report: meta is not a dict")
    return errors


def check_inconsistent_record(rec) -> list[str]:
    """Lint one inconsistency record (ScrubStore / MScrubCommand
    list-inconsistent-obj shape)."""
    from ceph_tpu.osd.scrub import KNOWN_ERRORS

    errors: list[str] = []
    if not isinstance(rec, dict):
        return ["inconsistent record: not a dict"]
    for field in INCONSISTENT_REQUIRED:
        if field not in rec:
            errors.append(
                f"inconsistent record: missing field {field!r}"
            )
    obj = rec.get("object")
    if not isinstance(obj, dict) or not isinstance(
        obj.get("name"), str
    ):
        errors.append(
            "inconsistent record: object.name missing or non-str"
        )
    elif len(obj["name"]) > INCONSISTENT_MAX_NAME or not (
        _LABEL_SAFE_RE.match(obj["name"])
    ):
        errors.append(
            f"inconsistent record: object name {obj['name']!r} "
            "unbounded or not label-safe"
        )
    for key in ("errors", "union_shard_errors"):
        vocab = rec.get(key, [])
        if not isinstance(vocab, list):
            errors.append(f"inconsistent record: {key} not a list")
            continue
        for e in vocab:
            if e not in KNOWN_ERRORS:
                errors.append(
                    f"inconsistent record: unknown error code {e!r}"
                )
    shards = rec.get("shards", [])
    if not isinstance(shards, list):
        errors.append("inconsistent record: shards not a list")
        shards = []
    if len(shards) > INCONSISTENT_MAX_SHARDS:
        errors.append(
            f"inconsistent record: over {INCONSISTENT_MAX_SHARDS} "
            "shards"
        )
    for sh in shards:
        if not isinstance(sh, dict) or not isinstance(
            sh.get("osd"), int
        ):
            errors.append(
                "inconsistent record: shard entry without int osd"
            )
            continue
        for e in sh.get("errors", []):
            if e not in KNOWN_ERRORS:
                errors.append(
                    f"inconsistent record: shard {sh['osd']} unknown "
                    f"error code {e!r}"
                )
    return errors


def product_scrub_samples() -> list[str]:
    """Run the REAL compare paths over synthetic scrub maps and lint
    the records they produce — the shapes ScrubStore persists and
    list-inconsistent-obj serves."""
    from ceph_tpu.osd.scrub import compare_ec, compare_replicated

    errors: list[str] = []
    base = {
        "exists": True, "size": 11, "omap_digest": 1,
        "attrs_digest": 2, "data_digest": 3,
    }
    rec = compare_replicated(
        "o_probe",
        {0: dict(base), 1: dict(base), 2: dict(base, data_digest=9)},
        primary=0,
        deep=True,
    )
    if rec is None:
        errors.append("compare_replicated: planted mismatch unfound")
    else:
        errors.extend(check_inconsistent_record(rec))
    ec_ent = {
        "exists": True, "size": 8, "omap_digest": 1,
        "attrs_digest": 2, "data_digest": 3,
        "hinfo": {"size": 16, "hashes": [3, 3, 9]},
    }
    rec, _needs = compare_ec(
        "o_probe",
        {0: dict(ec_ent), 1: dict(ec_ent), 2: dict(ec_ent)},
        acting=[0, 1, 2],
        sinfo=None,
        deep=True,
    )
    if rec is None:
        errors.append("compare_ec: planted shard mismatch unfound")
    else:
        errors.extend(check_inconsistent_record(rec))
    return errors


def check_scrub_counters() -> list[str]:
    """The OSD schema must keep declaring the scrub counter block the
    exporter's ceph_osd_scrub_* families are built from."""
    from ceph_tpu.osd.daemon import build_osd_perf

    declared = set(build_osd_perf(0)._counters)
    return [
        f"osd schema: scrub counter {name!r} missing"
        for name in SCRUB_COUNTERS
        if name not in declared
    ]


def check_fault_counters() -> list[str]:
    """The fault-plane families: every messenger's l_msgr_fault_*
    block and the OSD's fullness gauges — the chaos scenarios and the
    OSD_NEARFULL/OSD_FULL checks read exactly these."""
    from ceph_tpu.msg.faults import build_msgr_perf
    from ceph_tpu.osd.daemon import build_osd_perf

    errors = []
    msgr_declared = set(build_msgr_perf("lint")._counters)
    errors.extend(
        f"msgr schema: fault counter {name!r} missing"
        for name in FAULT_COUNTERS
        if name not in msgr_declared
    )
    osd_declared = set(build_osd_perf(0)._counters)
    errors.extend(
        f"osd schema: fullness gauge {name!r} missing"
        for name in FULLNESS_COUNTERS
        if name not in osd_declared
    )
    return errors


def check_worker_counters() -> list[str]:
    """The shared-stack plane: build_stack_perf must keep declaring
    the l_msgr_worker_* family (aggregates + every per-worker index
    up to the declared worker count) the scale harness and the mgr
    exporter read."""
    from ceph_tpu.msg.stack import build_stack_perf

    n = 3
    declared = set(build_stack_perf(n)._counters)
    errors = [
        f"stack schema: worker counter {name!r} missing"
        for name in WORKER_COUNTERS
        if name not in declared
    ]
    for i in range(n):
        errors.extend(
            f"stack schema: per-worker counter "
            f"{tmpl.format(i=i)!r} missing"
            for tmpl in WORKER_PER_INDEX_COUNTERS
            if tmpl.format(i=i) not in declared
        )
    return errors


def check_proc_counters() -> list[str]:
    """The process runtime: build_proc_perf must keep declaring the
    l_proc_* family, and build_stack_perf the dispatch-backpressure
    pair — the supervisor tests, the chaos process-kill scenario,
    and the mgr exporter read exactly these."""
    from ceph_tpu.msg.stack import build_stack_perf
    from ceph_tpu.proc.supervisor import build_proc_perf

    errors = []
    declared = set(build_proc_perf()._counters)
    errors.extend(
        f"proc schema: counter {name!r} missing"
        for name in PROC_COUNTERS
        if name not in declared
    )
    stack_declared = set(build_stack_perf(1)._counters)
    errors.extend(
        f"stack schema: dispatch-queue counter {name!r} missing"
        for name in DISPATCH_QUEUE_COUNTERS
        if name not in stack_declared
    )
    return errors


def check_recovery_counters() -> list[str]:
    """The recovery-storm plane: the OSD schema's l_osd_recovery_*
    block (bench.py's recovery section and the LRC fan-in assertion
    read exactly these)."""
    from ceph_tpu.osd.daemon import build_osd_perf

    declared = set(build_osd_perf(0)._counters)
    return [
        f"osd schema: recovery counter {name!r} missing"
        for name in RECOVERY_COUNTERS
        if name not in declared
    ]


def check_thrash_counters() -> list[str]:
    """The qa plane: build_thrash_perf must keep declaring the
    l_thrash_* family the smoke-thrash gate and repro reports
    count into."""
    from ceph_tpu.qa.thrasher import build_thrash_perf

    declared = set(build_thrash_perf()._counters)
    return [
        f"qa schema: counter {name!r} missing"
        for name in THRASH_COUNTERS
        if name not in declared
    ]


def check_objecter_counters() -> list[str]:
    """The client op path: build_objecter_perf must keep declaring
    the l_objecter_* family (backoff parks — the no-resend-storm
    witness the full-cluster scenarios assert on)."""
    from ceph_tpu.osdc.objecter import build_objecter_perf

    declared = set(build_objecter_perf()._counters)
    return [
        f"objecter schema: counter {name!r} missing"
        for name in OBJECTER_COUNTERS
        if name not in declared
    ]


def check_wal_counters() -> list[str]:
    """The WAL plane: build_wal_perf must keep declaring the
    l_os_wal_* family the bench wal section and the kill-storm chaos
    verdict read."""
    from ceph_tpu.store.wal_store import build_wal_perf

    declared = set(build_wal_perf()._counters)
    return [
        f"wal schema: counter {name!r} missing"
        for name in WAL_COUNTERS
        if name not in declared
    ]


def check_rgw_counters() -> list[str]:
    """The sharded-index plane: the gateway schema's
    ``l_rgw_index_*`` / ``l_rgw_reshard_*`` families, through the
    REAL builder."""
    from ceph_tpu.rgw.index import build_rgw_perf

    declared = set(build_rgw_perf("rgw")._counters)
    return [
        f"rgw schema: index counter {name!r} missing"
        for name in RGW_INDEX_COUNTERS
        if name not in declared
    ]


def check_residency_counters() -> list[str]:
    """The kernel-stats schema must keep declaring the residency and
    batched-encode families through the REAL registration helper
    (ops/residency.ensure_counters — the exact names the e2e_batched
    bench and the MMgrReport pipeline read)."""
    from ceph_tpu.ops.kernel_stats import KernelStats
    from ceph_tpu.ops.residency import ensure_counters

    ks = KernelStats()
    ensure_counters(ks)
    declared = set(ks.perf._counters)
    return [
        f"kernel schema: residency counter {name!r} missing"
        for name in RESIDENCY_COUNTERS
        if name not in declared
    ]


def check_dispatch_counters() -> list[str]:
    """The kernel-stats schema must keep declaring the
    flight-recorder family through the REAL registration helper
    (ops/profiler.ensure_dispatch_counters — the exact names the
    bench dispatch breakdown and the prometheus exporter read), with
    the stage-latency histograms carrying real bucket bounds."""
    from ceph_tpu.ops.kernel_stats import KernelStats
    from ceph_tpu.ops.profiler import ensure_dispatch_counters

    ks = KernelStats()
    ensure_dispatch_counters(ks)
    declared = set(ks.perf._counters)
    errors = [
        f"kernel schema: dispatch counter {name!r} missing"
        for name in DISPATCH_COUNTERS
        if name not in declared
    ]
    for stage in ("transfer", "compute", "sync"):
        name = f"l_tpu_dispatch_{stage}_lat_hist"
        c = ks.perf._counters.get(name)
        if c is not None and not getattr(c, "bucket_bounds", ()):
            errors.append(
                f"kernel schema: {name} histogram has no bucket "
                "bounds"
            )
    return errors


def product_event_samples() -> list[str]:
    """Generate one real clog entry and one real crash report through
    the product code paths and lint them — the schemas daemons
    actually emit, not hand-written fixtures."""
    from ceph_tpu.common import crash as crash_util
    from ceph_tpu.common.log_client import LogClient

    errors: list[str] = []
    client = LogClient("osd.0")
    entry = client.queue("cluster", "warn", "lint probe entry")
    errors.extend(check_clog_entry(entry))
    try:
        raise RuntimeError("lint probe crash")
    except RuntimeError as e:
        report = crash_util.build_report("osd.0", e)
    errors.extend(check_crash_report(report))
    return errors


_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
_SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_LABEL_PAIR_RE = re.compile(
    r'\s*(?P<k>[a-zA-Z_][a-zA-Z0-9_]*)="(?P<v>(?:[^"\\]|\\.)*)"\s*(?:,|$)'
)


def check_prometheus_histograms(text: str) -> list[str]:
    """Lint rendered exposition text for histogram-family
    correctness: one HELP/TYPE per family, cumulative bucket
    monotonicity per labelset, a closing ``le="+Inf"`` bucket that
    equals ``_count``, a ``_sum``/``_count`` pair per labelset, and
    label-name safety.  Fed the exporter's real output in tier-1."""
    errors: list[str] = []
    types: dict[str, str] = {}
    helped: set[str] = set()
    # (family, labels-without-le) -> [(le, value)] in document order
    buckets: dict[tuple[str, tuple], list[tuple[str, float]]] = {}
    sums: set[tuple[str, tuple]] = set()
    counts: dict[tuple[str, tuple], float] = {}
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            fam = parts[2] if len(parts) > 2 else ""
            if fam in types:
                errors.append(f"line {lineno}: duplicate TYPE {fam}")
            types[fam] = parts[3] if len(parts) > 3 else ""
            continue
        if line.startswith("# HELP "):
            parts = line.split()
            fam = parts[2] if len(parts) > 2 else ""
            if fam in helped:
                errors.append(f"line {lineno}: duplicate HELP {fam}")
            helped.add(fam)
            continue
        if line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(f"line {lineno}: unparseable sample {line!r}")
            continue
        name = m.group("name")
        labels: dict[str, str] = {}
        raw = m.group("labels") or ""
        pos = 0
        while pos < len(raw):
            lm = _LABEL_PAIR_RE.match(raw, pos)
            if lm is None:
                errors.append(
                    f"line {lineno}: bad label syntax {raw!r}"
                )
                break
            labels[lm.group("k")] = lm.group("v")
            pos = lm.end()
        for k in labels:
            if not _LABEL_NAME_RE.match(k):
                errors.append(f"line {lineno}: bad label name {k!r}")
        try:
            value = float(m.group("value"))
        except ValueError:
            errors.append(
                f"line {lineno}: non-numeric value "
                f"{m.group('value')!r}"
            )
            continue
        for suffix, sink in (
            ("_bucket", "bucket"), ("_sum", "sum"), ("_count", "count"),
        ):
            fam = name[: -len(suffix)] if name.endswith(suffix) else None
            if fam and types.get(fam) == "histogram":
                key = (
                    fam,
                    tuple(
                        sorted(
                            (k, v)
                            for k, v in labels.items()
                            if k != "le"
                        )
                    ),
                )
                if sink == "bucket":
                    if "le" not in labels:
                        errors.append(
                            f"line {lineno}: bucket without le"
                        )
                    buckets.setdefault(key, []).append(
                        (labels.get("le", ""), value)
                    )
                elif sink == "sum":
                    sums.add(key)
                else:
                    counts[key] = value
                break
    for fam, typ in types.items():
        if typ != "histogram":
            continue
        fam_keys = [k for k in buckets if k[0] == fam]
        if not fam_keys:
            errors.append(f"{fam}: histogram family with no buckets")
        for key in fam_keys:
            rows = buckets[key]
            vals = [v for _le, v in rows]
            if any(b > a for a, b in zip(vals[1:], vals)):
                errors.append(
                    f"{fam}{dict(key[1])}: buckets not monotone"
                )
            if not rows or rows[-1][0] != "+Inf":
                errors.append(
                    f"{fam}{dict(key[1])}: no closing +Inf bucket"
                )
            elif key in counts and rows[-1][1] != counts[key]:
                errors.append(
                    f"{fam}{dict(key[1])}: +Inf bucket "
                    f"{rows[-1][1]} != _count {counts[key]}"
                )
            if key not in sums:
                errors.append(f"{fam}{dict(key[1])}: missing _sum")
            if key not in counts:
                errors.append(f"{fam}{dict(key[1])}: missing _count")
    return errors


def product_histogram_exposition() -> list[str]:
    """Render histogram families through the mgr exporter's REAL
    renderer from product-generated histograms (op tracker
    completions + a commit histogram) and lint the text."""
    from ceph_tpu.common.histogram import LogHistogram
    from ceph_tpu.common.op_tracker import OpTracker
    from ceph_tpu.mgr import histogram_exposition_lines

    tracker = OpTracker()
    for qos, typ, n in (
        ("client", "write", 3), ("client", "read", 2),
        ("gold", "write", 1),
    ):
        for _ in range(n):
            op = tracker.create_op(
                "lint probe", op_type=typ, qos_class=qos
            )
            op.mark_event("started")
            op.finish()
    commit = LogHistogram()
    for v in (1e-4, 2e-3, 0.5):
        commit.add(v)
    lines: list[str] = []
    series = [
        (
            {
                "ceph_daemon": "osd.0",
                "qos_class": key.split(".")[1],
                "op_type": key.split(".")[2],
            },
            snap,
        )
        for key, snap in sorted(
            tracker.histogram_perf_entries().items()
        )
    ]
    lines.extend(
        histogram_exposition_lines(
            "ceph_osd_op_latency_seconds",
            "op completion latency by qos class and op type",
            series,
        )
    )
    lines.extend(
        histogram_exposition_lines(
            "ceph_daemon_commit_lat_hist_seconds",
            "commit latency",
            [({"ceph_daemon": "osd.0"}, commit.snapshot())],
        )
    )
    # a real flight-recorder stage histogram through the same
    # renderer: commit one dispatch on a private profiler and render
    # its sync-latency distribution as the exporter would
    from ceph_tpu.ops.kernel_stats import KernelStats
    from ceph_tpu.ops.profiler import DispatchProfiler

    dks = KernelStats()
    dprof = DispatchProfiler(capacity=8, ks=dks)
    with dprof.dispatch("crc32c", backend="jax") as dp:
        dp.set_ops(1)
        with dp.stage("sync"):
            pass
    snap = dks.dump().get("l_tpu_dispatch_sync_lat_hist")
    if not isinstance(snap, dict) or "bounds" not in snap:
        return [
            "dispatch sync lat_hist dump is not a histogram "
            f"snapshot: {snap!r}"
        ]
    lines.extend(
        histogram_exposition_lines(
            "ceph_daemon_tpu_dispatch_sync_lat_seconds",
            "device dispatch sync-stage latency",
            [({"ceph_daemon": "osd.0"}, snap)],
        )
    )
    text = "\n".join(lines) + "\n"
    errors = check_prometheus_histograms(text)
    if "le=\"+Inf\"" not in text:
        errors.append("exporter output carries no +Inf bucket at all")
    return errors


# PG-stats plane families the pgmap renderer must emit (mgr/pgmap.py
# pgmap_exposition_lines — `ceph_pg_total` is deliberately ABSENT:
# the exporter already serves it from pg_summary, and a second
# emission would be a duplicate family)
PGMAP_FAMILIES = (
    "ceph_pg_degraded",
    "ceph_pg_misplaced",
    "ceph_pg_unfound",
    "ceph_pg_state",
    "ceph_pool_stored_bytes",
    "ceph_pool_objects",
)
# families other exporter paths own; the pgmap renderer must never
# emit them (cross-set collision = duplicate HELP/TYPE in /metrics)
PGMAP_RESERVED = ("ceph_pg_total", "ceph_pool_pg_num")


def product_pgmap_exposition() -> list[str]:
    """Render the pgmap + progress families through the REAL
    renderer (mgr/pgmap.py pgmap_exposition_lines) from a synthetic
    digest and lint the text: every family present exactly once with
    a HELP/TYPE pair, parseable samples, label-safe values, and no
    collision with the families the exporter serves elsewhere."""
    from ceph_tpu.mgr.pgmap import pgmap_exposition_lines

    digest = {
        "totals": {
            "objects": 24, "bytes": 49152, "degraded": 3,
            "misplaced": 1, "unfound": 0,
        },
        "pg_states": {"active+clean": 7, "active+degraded": 1},
        "pools": {
            1: {"name": "da\"ta", "objects": 24, "bytes": 49152},
            2: {"name": "rbd", "objects": 0, "bytes": 0},
        },
    }
    text = "\n".join(pgmap_exposition_lines(digest)) + "\n"
    errors: list[str] = []
    helped: dict[str, int] = {}
    typed: dict[str, str] = {}
    sampled: set[str] = set()
    for lineno, line in enumerate(text.splitlines(), 1):
        line = line.strip()
        if not line:
            continue
        if line.startswith("# HELP "):
            fam = line.split()[2]
            helped[fam] = helped.get(fam, 0) + 1
            continue
        if line.startswith("# TYPE "):
            parts = line.split()
            typed[parts[2]] = parts[3] if len(parts) > 3 else ""
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            errors.append(
                f"pgmap line {lineno}: unparseable sample {line!r}"
            )
            continue
        sampled.add(m.group("name"))
        try:
            float(m.group("value"))
        except ValueError:
            errors.append(
                f"pgmap line {lineno}: non-numeric value "
                f"{m.group('value')!r}"
            )
        raw = m.group("labels") or ""
        pos = 0
        while pos < len(raw):
            lm = _LABEL_PAIR_RE.match(raw, pos)
            if lm is None:
                errors.append(
                    f"pgmap line {lineno}: bad label syntax {raw!r}"
                )
                break
            if not _LABEL_NAME_RE.match(lm.group("k")):
                errors.append(
                    f"pgmap line {lineno}: bad label name "
                    f"{lm.group('k')!r}"
                )
            pos = lm.end()
    for fam in PGMAP_FAMILIES:
        if fam not in sampled:
            errors.append(f"pgmap family {fam} emitted no samples")
        if helped.get(fam, 0) != 1:
            errors.append(
                f"pgmap family {fam}: {helped.get(fam, 0)} HELP "
                "headers (want exactly 1)"
            )
        if typed.get(fam) != "gauge":
            errors.append(
                f"pgmap family {fam}: TYPE {typed.get(fam)!r} "
                "(want gauge)"
            )
    for fam in PGMAP_RESERVED:
        if fam in sampled or fam in typed:
            errors.append(
                f"pgmap renderer emits {fam}, which another "
                "exporter path owns (duplicate family in /metrics)"
            )
    return errors


def check_perf_counters(pc) -> list[str]:
    """Lint one PerfCounters set; returns human-readable errors."""
    from ceph_tpu.common.perf_counters import PERFCOUNTER_HISTOGRAM

    errors: list[str] = []
    seen: set[str] = set()
    for name, counter in pc._counters.items():
        where = f"{pc.name}.{name}"
        if name in seen:
            errors.append(f"{where}: duplicate counter name")
        seen.add(name)
        if counter.name != name:
            errors.append(
                f"{where}: registered under {counter.name!r}"
            )
        if not _NAME_RE.match(name.replace(".", "_")):
            errors.append(
                f"{where}: invalid Prometheus metric characters"
            )
        if counter.kind == PERFCOUNTER_HISTOGRAM and not list(
            counter.bucket_bounds
        ):
            errors.append(
                f"{where}: histogram with no bucket bounds"
            )
    if not _NAME_RE.match(pc.name.replace(".", "_")):
        errors.append(
            f"{pc.name}: set name has invalid Prometheus characters"
        )
    return errors


def product_counter_sets():
    """Every schema the product registers (import side effects force
    lazy groups into existence so the lint sees the real shape)."""
    from ceph_tpu.msg.faults import build_msgr_perf
    from ceph_tpu.msg.stack import build_stack_perf, default_workers
    from ceph_tpu.ops.kernel_stats import KernelStats
    from ceph_tpu.osd.daemon import build_osd_perf
    from ceph_tpu.osd.mapping import _build_perf as build_mapping_perf
    from ceph_tpu.osdc.objecter import build_objecter_perf
    from ceph_tpu.proc.supervisor import build_proc_perf
    from ceph_tpu.qa.thrasher import build_thrash_perf
    from ceph_tpu.rgw.index import build_rgw_perf
    from ceph_tpu.store.wal_store import build_wal_perf

    from ceph_tpu.ops.residency import ensure_counters

    ks = KernelStats()
    # force-register every group the instrumented modules use
    for group in ("ec_encode", "ec_decode", "gf_matmul",
                  "gf_bitmatrix", "crush"):
        ks.record(group)
    ks.counter("crush", "pgs")
    # residency + coalesced-encode families (ops/residency.py) join
    # the schema walk and the cross-set collision lint
    ensure_counters(ks)
    # flight-recorder family (ops/profiler.py) likewise
    from ceph_tpu.ops.profiler import ensure_dispatch_counters

    ensure_dispatch_counters(ks)
    return [
        build_osd_perf(0), build_mapping_perf(), ks.perf,
        build_msgr_perf("osd.0"),
        build_stack_perf(default_workers()),
        build_rgw_perf("rgw"),
        build_wal_perf(),
        build_proc_perf(),
        build_thrash_perf(),
        build_objecter_perf(),
    ]


def check_all(sets=None) -> list[str]:
    lint_events = sets is None
    sets = product_counter_sets() if sets is None else sets
    errors: list[str] = []
    cross: set[str] = set()
    for pc in sets:
        errors.extend(check_perf_counters(pc))
        for name in pc._counters:
            key = f"{pc.name}.{name}".replace(".", "_")
            if key in cross:
                errors.append(
                    f"{pc.name}.{name}: collides with another set "
                    "after exporter name-flattening"
                )
            cross.add(key)
    if lint_events:
        # product mode (no explicit sets): also lint the event-plane
        # and scrub-plane schemas the daemons really emit, and the
        # exporter's native histogram rendering
        errors.extend(product_event_samples())
        errors.extend(product_scrub_samples())
        errors.extend(check_scrub_counters())
        errors.extend(check_fault_counters())
        errors.extend(check_worker_counters())
        errors.extend(check_residency_counters())
        errors.extend(check_dispatch_counters())
        errors.extend(check_proc_counters())
        errors.extend(check_thrash_counters())
        errors.extend(check_objecter_counters())
        errors.extend(check_recovery_counters())
        errors.extend(check_rgw_counters())
        errors.extend(check_wal_counters())
        errors.extend(product_histogram_exposition())
        errors.extend(product_pgmap_exposition())
    return errors


def main() -> int:
    errors = check_all()
    for err in errors:
        print(f"check_metrics: {err}", file=sys.stderr)
    if errors:
        print(f"check_metrics: {len(errors)} error(s)", file=sys.stderr)
        return 1
    print("check_metrics: all counter schemas clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
