#!/usr/bin/env python
"""Metrics-schema lint — walk every registered PerfCounters schema
and fail on exporter-breaking declarations (run in tier-1 via
tests/test_observability.py, and standalone as
``python tools/check_metrics.py``).

Checks, per counter set:

- duplicate counter names within a set (the builder asserts at
  declaration time; dynamically-extended sets — KernelStats — can
  bypass it) and duplicate (set, counter) pairs across sets after the
  exporter's name transformation;
- names that the Prometheus exposition format rejects: anything
  outside ``[a-zA-Z_:][a-zA-Z0-9_:]*`` AFTER the mgr exporter's
  sanitization would silently collide or be dropped — the lint flags
  the raw name so the collision is fixed at the source;
- histogram counters with no bucket bounds (an unbounded histogram
  dumps an empty bucket array and renders as a zero-information
  series).

The walked schemas are the product's real ones: the OSD daemon's
counter block, the batched-mapping counters, and the device-kernel
telemetry plane (after forcing registration of every group).
"""

from __future__ import annotations

import re
import sys

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")


def check_perf_counters(pc) -> list[str]:
    """Lint one PerfCounters set; returns human-readable errors."""
    from ceph_tpu.common.perf_counters import PERFCOUNTER_HISTOGRAM

    errors: list[str] = []
    seen: set[str] = set()
    for name, counter in pc._counters.items():
        where = f"{pc.name}.{name}"
        if name in seen:
            errors.append(f"{where}: duplicate counter name")
        seen.add(name)
        if counter.name != name:
            errors.append(
                f"{where}: registered under {counter.name!r}"
            )
        if not _NAME_RE.match(name.replace(".", "_")):
            errors.append(
                f"{where}: invalid Prometheus metric characters"
            )
        if counter.kind == PERFCOUNTER_HISTOGRAM and not list(
            counter.bucket_bounds
        ):
            errors.append(
                f"{where}: histogram with no bucket bounds"
            )
    if not _NAME_RE.match(pc.name.replace(".", "_")):
        errors.append(
            f"{pc.name}: set name has invalid Prometheus characters"
        )
    return errors


def product_counter_sets():
    """Every schema the product registers (import side effects force
    lazy groups into existence so the lint sees the real shape)."""
    from ceph_tpu.ops.kernel_stats import KernelStats
    from ceph_tpu.osd.daemon import build_osd_perf
    from ceph_tpu.osd.mapping import _build_perf as build_mapping_perf

    ks = KernelStats()
    # force-register every group the instrumented modules use
    for group in ("ec_encode", "ec_decode", "gf_matmul",
                  "gf_bitmatrix", "crush"):
        ks.record(group)
    ks.counter("crush", "pgs")
    return [build_osd_perf(0), build_mapping_perf(), ks.perf]


def check_all(sets=None) -> list[str]:
    sets = product_counter_sets() if sets is None else sets
    errors: list[str] = []
    cross: set[str] = set()
    for pc in sets:
        errors.extend(check_perf_counters(pc))
        for name in pc._counters:
            key = f"{pc.name}.{name}".replace(".", "_")
            if key in cross:
                errors.append(
                    f"{pc.name}.{name}: collides with another set "
                    "after exporter name-flattening"
                )
            cross.add(key)
    return errors


def main() -> int:
    errors = check_all()
    for err in errors:
        print(f"check_metrics: {err}", file=sys.stderr)
    if errors:
        print(f"check_metrics: {len(errors)} error(s)", file=sys.stderr)
        return 1
    print("check_metrics: all counter schemas clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
